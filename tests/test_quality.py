"""Quality-observability tests (ISSUE 9): the online recall sentinel,
index-health introspection, the SLO engine, the guarded-site drift
guard, hostile-payload event export — and the end-to-end acceptance
drill: a fault-injected demotion on a quantized CAGRA searcher must
produce a measurable ``serve.recall`` drop, a trace-stamped
``recall_regression`` event, and an SLO breach verdict in the debugz
snapshot.

Everything except the acceptance drill runs on numpy stubs or handmade
indexes (no XLA compiles); the drill builds ONE tiny CAGRA index and
compiles two small search shapes.
"""
import json
import pathlib
import re
import time

import jax
import numpy as np
import pytest

from ann_utils import naive_knn
from raft_tpu.core import events, faults, tracing
from raft_tpu.serve import debugz, metrics, quality, slo
from raft_tpu.serve.batcher import BucketLadder, MicroBatcher
from raft_tpu.serve.quality import RecallSentinel

pytestmark = pytest.mark.serve

DIM = 16


@pytest.fixture
def reg():
    return metrics.Registry()


@pytest.fixture(autouse=True)
def _clean_rings():
    events.clear()
    tracing.clear_span_log()
    yield


def np_reference(data):
    """Exact numpy reference closure for the sentinel (zero compiles)."""
    return lambda q, k: naive_knn(np.asarray(data), np.asarray(q), k)


def _serve_result(data, q, k):
    d, i = naive_knn(np.asarray(data), np.asarray(q), k)
    return d.astype(np.float32), i.astype(np.int32)


class TestRecallSentinel:
    def test_disabled_is_one_flag_check(self, reg, monkeypatch):
        monkeypatch.delenv("RAFT_TPU_RECALL_SAMPLE", raising=False)

        def ref(q, k):  # pragma: no cover - must never run
            raise AssertionError("reference executed while disabled")

        s = RecallSentinel(ref, registry=reg)
        assert not s.enabled and s._thread is None   # no worker thread
        assert not s.offer(np.zeros((2, 4), np.float32), 2,
                           None, np.zeros((2, 2), np.int32))
        assert s.estimate() is None
        # env knob resolves through the shared validated parser
        monkeypatch.setenv("RAFT_TPU_RECALL_SAMPLE", "0.5")
        assert RecallSentinel(ref, registry=reg, autostart=False)._every == 2
        monkeypatch.setenv("RAFT_TPU_RECALL_SAMPLE", "1.5")
        with pytest.raises(ValueError):
            RecallSentinel(ref, registry=reg)

    def test_ceil_cadence_never_exceeds_rate(self, reg):
        # 0.7 must sample every 2nd offer, never 100% (the knob bounds
        # the reference-work budget from above)
        s = RecallSentinel(np_reference(np.zeros((8, 4), np.float32)),
                           sample=0.7, registry=reg, autostart=False)
        assert s._every == 2
        q = np.zeros((2, 4), np.float32)
        taken = [s.offer(q, 2, None, np.zeros((2, 2), np.int32))
                 for _ in range(6)]
        assert taken == [True, False, True, False, True, False]

    def test_rolling_estimates_per_family_and_engine(self, reg):
        rng = np.random.default_rng(3)
        data = rng.standard_normal((64, 8)).astype(np.float32)
        q = data[:6]
        d, i = _serve_result(data, q, 4)
        bad = np.full_like(i, -1)
        with RecallSentinel(np_reference(data), sample=1.0, window=8,
                            registry=reg, family="famA",
                            engine="e1") as s:
            assert s.offer(q, 4, d, i, trace_id="t0")
            s.offer(q, 4, None, bad, family="famB", engine="e2")
            assert s.drain(30)
        assert s.estimate("famA") == pytest.approx(1.0)
        assert s.estimate("famB") == pytest.approx(0.0)
        g = reg.snapshot()["gauges"]
        assert g["serve.recall.famA"] == pytest.approx(1.0)
        assert g["serve.recall.famA.e1"] == pytest.approx(1.0)
        assert g["serve.recall.famB.e2"] == pytest.approx(0.0)
        assert g["serve.recall.famA.samples"] == 1
        snap = s.snapshot()
        assert snap["families"]["famA"]["engines"]["e1"] == 1.0

    def test_regression_event_once_per_crossing_and_rearm(self, reg):
        data = np.random.default_rng(4).standard_normal(
            (32, 8)).astype(np.float32)
        q = data[:4]
        d, i = _serve_result(data, q, 4)
        bad = np.full_like(i, -1)
        with RecallSentinel(np_reference(data), sample=1.0, floor=0.8,
                            window=2, min_samples=1, registry=reg,
                            family="f") as s:
            s.offer(q, 4, d, i, trace_id="good")
            assert s.drain(30)
            assert not events.recent(kind="recall_regression")
            s.offer(q, 4, None, bad, trace_id="bad1")
            s.offer(q, 4, None, bad, trace_id="bad2")   # still below: no 2nd
            assert s.drain(30)
            evs = events.recent(kind="recall_regression")
            assert len(evs) == 1
            assert evs[0]["site"] == "serve.recall.f"
            assert evs[0]["trace_id"] == "bad1"
            assert evs[0]["floor"] == 0.8
            # recovery re-arms the crossing detector
            s.offer(q, 4, d, i)
            s.offer(q, 4, d, i)
            assert s.drain(30)
            assert s.estimate("f") == pytest.approx(1.0)
            s.offer(q, 4, None, bad, trace_id="bad3")
            s.offer(q, 4, None, bad)
            assert s.drain(30)
        assert len(events.recent(kind="recall_regression")) == 2
        assert reg.snapshot()["counters"]["serve.recall.regressions"] == 2

    def test_saturated_queue_drops_never_blocks(self, reg):
        """Micro-benchmark satellite: a stalled worker must cost drops,
        not latency — and the disabled/enabled hot-path stays cheap."""
        data = np.zeros((8, 4), np.float32)
        s = RecallSentinel(np_reference(data), sample=1.0, max_pending=4,
                           registry=reg, autostart=False)   # stalled worker
        q = np.zeros((2, 4), np.float32)
        i = np.zeros((2, 2), np.int32)
        t0 = time.perf_counter()
        n = 500
        for _ in range(n):
            s.offer(q, 2, None, i)
        enabled_per_call = (time.perf_counter() - t0) / n
        snap = s.snapshot()
        assert snap["pending"] == 4
        assert snap["dropped"] == n - 4
        assert reg.snapshot()["counters"]["serve.recall.dropped"] == n - 4
        # saturated offers must stay far below any blocking timescale
        # (generous absolute bound: the 1-core CI box is noisy)
        assert enabled_per_call < 2e-3, (
            f"saturated offer cost {enabled_per_call:.2e}s/call — "
            "the sentinel is blocking dispatch")
        off = RecallSentinel(np_reference(data), sample=0.0, registry=reg)

        def bench(fn, n=20000):
            best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                for _ in range(n):
                    fn()
                best = min(best, (time.perf_counter() - t0) / n)
            return best

        base = bench(lambda: None)
        cost = bench(lambda: off.offer(q, 2, None, i))
        assert cost - base < 20e-6, (
            f"disabled sentinel offer overhead {cost - base:.2e}s/call — "
            "the disabled path must be one flag check")
        # stopped is not pressure: offers after close() return False but
        # must NOT climb the dropped counter (a dashboard would read a
        # stopped sentinel as a saturated one forever)
        s.close()
        dropped = reg.snapshot()["counters"]["serve.recall.dropped"]
        assert not s.offer(q, 2, None, i)
        assert reg.snapshot()["counters"]["serve.recall.dropped"] == dropped


class TestHealth:
    def test_cagra_health_connectivity_and_quant(self):
        from raft_tpu.neighbors import cagra

        n, deg = 64, 4
        rng = np.random.default_rng(0)
        data = rng.standard_normal((n, 8)).astype(np.float32)
        # every node's edges stay in [0, 62]: node 63 has in-degree 0
        g = (np.arange(n)[:, None] + np.arange(1, deg + 1)[None, :]) % (n - 1)
        idx = cagra.Index(jax.numpy.asarray(data),
                          jax.numpy.asarray(g.astype(np.int32)),
                          cagra.DistanceType.L2Expanded)
        h = cagra.health(idx)
        assert h["family"] == "cagra" and h["n"] == n
        assert h["graph_degree"] == deg
        assert h["unreachable_nodes"] == 1
        assert h["unseeded_unreachable"] == 1
        assert h["in_degree"]["min"] == 0 and h["in_degree"]["mean"] > 0
        # the connectivity summary caches on the index (a watched 1M
        # index must not re-pull its whole graph every snapshot) ...
        assert getattr(idx, "_health_conn_cache", None) is not None
        # ... and invalidates when the seed set changes: a covering seed
        # set claims the unreachable node
        idx.seed_nodes = jax.numpy.asarray([63], jax.numpy.int32)
        h2 = cagra.health(idx)
        assert h2["unreachable_nodes"] == 1
        assert h2["unseeded_unreachable"] == 0
        # quantized traversal caches report MEASURED reconstruction error
        cagra.prepare_search(idx, "int8")
        cagra.prepare_search(idx, "bfloat16")
        h3 = cagra.health(idx)
        assert 0 < h3["quant"]["int8"]["rel_rmse"] < 0.02
        assert 0 < h3["quant"]["bfloat16"]["rel_rmse"] < 0.02

    def test_ivf_flat_health_skew_and_scales(self):
        from raft_tpu.neighbors import ivf_flat
        from raft_tpu.neighbors._list_layout import plan_offsets

        sizes = np.array([10, 20, 30, 0], np.int64)
        offsets = plan_offsets(sizes)
        cap = int(offsets[-1])
        sid = np.full(cap, -1, np.int32)
        for l, (o, s) in enumerate(zip(offsets[:-1], sizes)):
            sid[o:o + s] = np.arange(s)
        idx = ivf_flat.Index(
            data=np.zeros((cap, 8), np.int8),
            data_norms=np.zeros(cap, np.float32),
            source_ids=sid,
            centers=np.zeros((4, 8), np.float32),
            center_norms=np.zeros(4, np.float32),
            list_offsets=offsets,
            metric=ivf_flat.DistanceType.L2Expanded,
            list_sizes_arr=sizes,
            scales=np.full(cap, 0.25, np.float32))
        h = ivf_flat.health(idx)
        assert h["n"] == 60 and h["store_dtype"] == "int8"
        lk = h["lists"]
        assert lk["n_lists"] == 4 and lk["empty_lists"] == 1
        assert lk["max"] == 30 and lk["max_over_mean"] == 2.0
        assert h["quant"]["int8"]["max_abs_err_bound"] == 0.125

    def test_ivf_pq_health_utilization(self):
        from raft_tpu.neighbors import ivf_pq

        cap, pq_dim, bits = 64, 4, 4
        rng = np.random.default_rng(1)
        codes = rng.integers(0, 16, size=(cap, pq_dim)).astype(np.uint8)
        codes[:, 3] = 5          # one collapsed subspace
        idx = ivf_pq.Index(
            codes=jax.numpy.asarray(codes),
            source_ids=jax.numpy.arange(cap, dtype=jax.numpy.int32),
            centers_rot=jax.numpy.zeros((4, 8)),
            codebooks=jax.numpy.zeros((pq_dim, 1 << bits, 2)),
            rotation=jax.numpy.zeros((8, 8)),
            list_offsets=np.array([0, 16, 32, 48, 64], np.int64),
            metric=ivf_pq.DistanceType.L2Expanded,
            pq_bits=bits,
            codebook_kind=ivf_pq.CodebookGen.PER_SUBSPACE)
        h = ivf_pq.health(idx)
        assert h["pq"]["pq_dim"] == pq_dim and h["pq"]["book_size"] == 16
        util = h["pq"]["codeword_utilization"]
        assert util["min"] == pytest.approx(1 / 16)     # collapsed subspace
        assert util["mean"] > 0.5
        assert h["lists"]["rows"] == cap

    def test_sharded_health_counts_and_flags(self):
        from raft_tpu.parallel import sharded_ann
        from jax.sharding import Mesh

        mesh = Mesh(np.array(jax.devices()[:2]), ("shard",))
        idx = sharded_ann.ShardedCagra(
            mesh, data=np.zeros((2, 8, 4), np.float32),
            graphs=np.zeros((2, 8, 2), np.int32),
            bases=np.array([0, 5], np.int32),
            counts=np.array([5, 3], np.int32), n_total=8,
            metric=sharded_ann.DistanceType.L2Expanded)
        idx.mark_shard_failed(1)
        h = quality.health(idx)      # the dispatcher route
        assert h["family"] == "sharded_cagra"
        assert h["shard_rows"] == [5, 3]
        assert h["shards_ok"] == [True, False]
        assert h["served_rows"] == 5
        assert h["served_frac"] == pytest.approx(5 / 8)

    def test_watch_index_weak_and_jsonl_export(self, tmp_path):
        from raft_tpu.neighbors import brute_force

        data = np.random.default_rng(2).standard_normal(
            (32, 8)).astype(np.float32)
        idx = brute_force.build(jax.numpy.asarray(data),
                                dtype=jax.numpy.int8)
        quality.watch_index("unit_bf", idx)
        try:
            snap = quality.health_snapshot()
            assert snap["unit_bf"]["family"] == "brute_force"
            assert "int8" in snap["unit_bf"]["quant"]
            path = tmp_path / "health.jsonl"
            assert quality.export_health_jsonl(str(path)) >= 1
            line = json.loads(path.read_text().splitlines()[0])
            assert line["index"] == "unit_bf" and line["family"] == "brute_force"
            # debugz surfaces the same report
            d = debugz.snapshot(registry=metrics.Registry())
            assert d["health"]["unit_bf"]["n"] == 32
            text = debugz.render_text(registry=metrics.Registry())
            assert "index health" in text and "unit_bf" in text
        finally:
            quality.unwatch_index("unit_bf")
        # weak: dropping the index drops the watch
        quality.watch_index("gone", idx)
        del idx
        import gc

        gc.collect()
        assert "gone" not in quality.health_snapshot()
        quality.unwatch_index("gone")


class TestSLOEngine:
    def test_burn_rate_windows_and_breach_transitions(self, reg):
        now = {"t": 0.0}
        eng = slo.SLOEngine(
            slo.Targets(max_shed_rate=0.1), registry=reg, name="u",
            fast_window_s=10.0, slow_window_s=60.0,
            clock=lambda: now["t"])
        req = reg.counter("u.requests")
        shed = reg.counter("u.shed")
        req.inc(100)
        eng.tick()
        now["t"] = 5.0
        req.inc(100)
        assert eng.evaluate()["verdict"] == "ok"
        # a shed burst violates BOTH windows -> breach + ONE event
        now["t"] = 12.0
        req.inc(100)
        shed.inc(50)
        rep = eng.evaluate()
        assert rep["targets"]["shed_rate"]["verdict"] == "breach"
        assert rep["verdict"] == "breach"
        assert len(events.recent(kind="slo_breach")) == 1
        assert events.recent(kind="slo_breach")[0]["site"] == "u.slo.shed_rate"
        # still breached: no duplicate event
        now["t"] = 13.0
        eng.evaluate()
        assert len(events.recent(kind="slo_breach")) == 1
        # fast window recovers first: warn (burning off), then ok
        now["t"] = 30.0
        req.inc(200)
        rep = eng.evaluate()
        assert rep["targets"]["shed_rate"]["verdict"] == "warn"
        now["t"] = 100.0
        req.inc(100)
        rep = eng.evaluate()
        assert rep["targets"]["shed_rate"]["verdict"] == "ok"
        assert reg.snapshot()["counters"]["u.slo.breaches"] == 1

    def test_windowed_latency_p99(self, reg):
        now = {"t": 0.0}
        eng = slo.SLOEngine(
            slo.Targets(p99_latency_s=0.5), registry=reg, name="u",
            fast_window_s=10.0, slow_window_s=10.0,
            clock=lambda: now["t"])
        h = reg.histogram("u.latency_s")
        for _ in range(100):
            h.observe(0.001)
        eng.tick()
        now["t"] = 20.0
        assert eng.evaluate()["targets"]["p99_latency_s"]["verdict"] == "ok"
        # the RECENT window is slow even though the lifetime p99 is fine
        for _ in range(50):
            h.observe(2.0)
        now["t"] = 40.0
        rep = eng.evaluate()["targets"]["p99_latency_s"]
        assert rep["fast"] > 0.5 and rep["verdict"] == "breach"

    def test_recall_target_gates_on_samples(self, reg):
        eng = slo.SLOEngine(
            slo.Targets(recall_floor=0.9, recall_family="f",
                        recall_min_samples=2), registry=reg, name="u")
        rep = eng.evaluate()["targets"]["recall"]
        assert rep["verdict"] == "ok" and rep["note"] == "insufficient_samples"
        reg.gauge("u.recall.f").set(0.95)
        reg.gauge("u.recall.f.samples").set(8)
        assert eng.evaluate()["targets"]["recall"]["verdict"] == "ok"
        reg.gauge("u.recall.f").set(0.91)
        assert eng.evaluate()["targets"]["recall"]["verdict"] == "warn"
        reg.gauge("u.recall.f").set(0.5)
        rep = eng.evaluate()
        assert rep["targets"]["recall"]["verdict"] == "breach"
        assert events.recent(kind="slo_breach")[-1]["site"] == "u.slo.recall"
        # installed engine rides into the debugz snapshot
        eng.install()
        try:
            snap = debugz.snapshot(registry=reg)
            assert snap["slo"]["targets"]["recall"]["verdict"] == "breach"
            assert "-- slo (breach) --" in debugz.render_text(registry=reg)
        finally:
            slo.uninstall()


class TestEventsScrub:
    def test_to_jsonl_hostile_payloads_never_raise(self, tmp_path):
        events.record(
            "hostile", "unit.site",
            nanv=float("nan"), infv=float("inf"), neg=-float("inf"),
            arr=np.arange(5, dtype=np.int32),
            big=np.zeros((100, 100), np.float32),
            npf=np.float32(1.5), npi=np.int64(7),
            exc=ValueError("boom"),
            nested={"x": [float("nan"), 1.0], 3: (np.float64("inf"),)},
            obj=object())
        line = events.to_jsonl(kind="hostile")
        assert "NaN" not in line and "Infinity" not in line
        rec = json.loads(line)
        assert rec["nanv"] is None and rec["infv"] is None
        assert rec["arr"] == [0, 1, 2, 3, 4]
        assert rec["big"].startswith("array(shape=(100, 100)")
        assert rec["npf"] == 1.5 and rec["npi"] == 7
        assert rec["exc"] == "ValueError: boom"
        assert rec["nested"]["x"] == [None, 1.0]
        assert rec["nested"]["3"] == [None]
        path = tmp_path / "ev.jsonl"
        assert events.export_jsonl(str(path)) >= 1
        for ln in path.read_text().splitlines():
            json.loads(ln)
        # the debugz snapshot stays strict-JSON-safe with these in the ring
        json.dumps(debugz.snapshot(registry=metrics.Registry()),
                   allow_nan=False)


class TestGuardedDriftGuard:
    # the sites the current tree must keep gated; the sweep below also
    # catches NEW guarded_call sites automatically
    KNOWN = {"select_k.kpass", "ivf_flat.scan", "ivf_pq.scan",
             "brute_force.fused", "cagra.graph_expand",
             "cagra.fused_search", "cagra.nn_descent",
             "sharded.ring_topk", "mutable.merge",
             "filter.survivor_brute"}

    def _discover_sites(self):
        import raft_tpu

        root = pathlib.Path(raft_tpu.__file__).parent
        sites = set()
        for p in root.rglob("*.py"):
            src = p.read_text()
            sites |= set(re.findall(r'guarded_call\(\s*\n?\s*"([^"]+)"', src))
            # constants passed as the site argument (the sharded merge's
            # MERGE_SITE, the fused traversal's FUSED_SITE, ...)
            sites |= set(re.findall(
                r'^(?:MERGE|FUSED)_SITE\s*=\s*"([^"]+)"', src,
                re.MULTILINE))
        return sites

    def test_every_site_has_breaker_policy(self):
        """ISSUE 10 drift guard: every guarded_call site must ship a
        breaker policy (ops/guarded.POLICIES) — a gated kernel without a
        declared recovery cadence is a one-way demotion by accident."""
        from raft_tpu.ops import guarded

        sites = self._discover_sites()
        assert self.KNOWN <= sites, (
            f"guarded sites missing from source sweep: {self.KNOWN - sites}")
        missing = sites - set(guarded.POLICIES)
        assert not missing, (
            f"guarded sites without a breaker policy: {sorted(missing)} — "
            "add them to ops/guarded.POLICIES (DEFAULT_POLICY is fine) so "
            "the recovery drill below exercises them")

    def test_every_site_demotes_probes_and_recovers(self, monkeypatch):
        """Every guarded_call site in the tree is drilled through the
        FULL breaker arc — demote (flight-recorder event + total and
        per-site counters), clock-stepped probation, failed probe
        (backoff doubles), successful probe (breaker re-closes, kernel
        path restored). A silent demotion is exactly the failure mode
        the recall sentinel exists to catch; a demotion that can never
        recover is the failure mode ISSUE 10 exists to close."""
        from raft_tpu.ops import guarded

        if any(f.kind in ("kernel_compile", "kernel_fault")
               for f in faults.active()):
            pytest.skip("ambient kernel faults are served as injected "
                        "(non-demoting) failures")
        sites = self._discover_sites()
        now = {"t": 0.0}
        monkeypatch.setattr(guarded, "_clock", lambda: now["t"])
        pre_demoted = set(guarded.demoted_sites())
        try:
            for site in sorted(sites - pre_demoted):
                total0 = metrics.counter("guarded.demotions").value
                site0 = metrics.counter(f"guarded.demotions.{site}").value

                def boom():
                    raise RuntimeError("drift-guard drill")

                # demote
                assert guarded.guarded_call(site, boom, lambda: "fb") == "fb"
                assert site in guarded.demoted_sites()
                evs = [e for e in events.recent(kind="guarded_demotion")
                       if e["site"] == site]
                assert evs, f"site {site} demoted without a ring event"
                assert metrics.counter("guarded.demotions").value \
                    == total0 + 1, f"site {site}: total counter"
                assert metrics.counter(
                    f"guarded.demotions.{site}").value == site0 + 1, \
                    f"site {site}: per-site counter"
                # inside probation: fallback without touching the kernel
                assert guarded.guarded_call(
                    site, boom, lambda: "fb") == "fb"
                b = guarded.breaker_snapshot()[site]
                assert b["state"] == "open" and b["probes"] == 0
                # probation expires -> one probe; failure doubles backoff
                now["t"] += b["next_probe_in_s"] + 0.1
                assert guarded.guarded_call(
                    site, boom, lambda: "fb") == "fb"
                b2 = guarded.breaker_snapshot()[site]
                assert b2["probes"] == 1 and \
                    b2["backoff_s"] == pytest.approx(2 * b["backoff_s"]), \
                    f"site {site}: failed probe must double the backoff"
                # next probe succeeds -> breaker closes, kernel restored
                now["t"] += b2["next_probe_in_s"] + 0.1
                assert guarded.guarded_call(
                    site, lambda: "kern", lambda: "fb") == "kern"
                assert site not in guarded.demoted_sites(), \
                    f"site {site}: breaker did not re-close"
                assert any(e["site"] == site for e in
                           events.recent(kind="breaker_close")), \
                    f"site {site}: recovery without a breaker_close event"
                assert guarded.guarded_call(
                    site, lambda: "kern", lambda: "fb") == "kern", \
                    f"site {site}: kernel path not restored after close"
        finally:
            guarded.reset()


class TestAcceptanceDrill:
    """ISSUE 9 acceptance: fault-injected demotion drill on a quantized
    CAGRA searcher -> measurable serve.recall drop + trace-stamped
    recall_regression + SLO breach in the debugz snapshot."""

    def test_end_to_end_quality_alarm(self, reg):
        from raft_tpu.neighbors import brute_force, cagra
        from raft_tpu.ops import guarded

        if any(f.kind == "kernel_compile" for f in faults.active()):
            pytest.skip("ambient kernel faults would degrade the healthy "
                        "phase too")
        rng = np.random.default_rng(7)
        centers = rng.standard_normal((8, DIM)).astype(np.float32) * 4.0
        labels = rng.integers(0, 8, size=400)
        data = (centers[labels]
                + rng.standard_normal((400, DIM))).astype(np.float32)
        q = (centers[rng.integers(0, 8, size=96)]
             + rng.standard_normal((96, DIM))).astype(np.float32)

        # the QUANTIZED cagra searcher (int8 traversal scoring)
        index = cagra.build(data, cagra.IndexParams(
            graph_degree=8, intermediate_graph_degree=16, seed=0,
            seed_nodes=0))
        sp = cagra.SearchParams(itopk_size=32, candidate_dtype="int8")
        good = cagra.make_searcher(index, sp)
        # the degraded mode a demotion serves: a stale quarter-corpus
        # replica (the partial-replica analog of a dead shard)
        stale = brute_force.build(jax.numpy.asarray(data[:100]))

        def serving(queries, k, res=None):
            return guarded.guarded_call(
                "drill.cagra.search",
                lambda: good(queries, k, res),
                lambda: brute_force.search(stale, queries, k))

        sentinel = RecallSentinel(
            np_reference(data), sample=1.0, floor=0.7, window=6,
            min_samples=3, max_pending=32, registry=reg,
            family="cagra", engine="int8")
        eng = slo.SLOEngine(
            slo.Targets(recall_floor=0.7, recall_family="cagra",
                        recall_min_samples=3),
            registry=reg, name="serve")
        quality.watch_index("drill_cagra", index)
        b = MicroBatcher(serving, DIM, ladder=BucketLadder((8,), (8,)),
                         registry=reg, max_wait_s=0.001, sentinel=sentinel)
        try:
            # phase A: healthy quantized serving
            for j in range(6):
                b.search(q[8 * j: 8 * (j + 1)], 8, timeout=120)
            assert sentinel.drain(60)
            est_good = sentinel.estimate("cagra")
            assert est_good is not None and est_good >= 0.75, est_good
            rep = eng.evaluate()
            assert rep["targets"]["recall"]["verdict"] == "ok"
            assert not events.recent(kind="recall_regression")

            # phase B: the demotion drill — every call served through
            # the degraded fallback
            drill_reqs = []
            with faults.inject("kernel_compile", "drill.cagra.search"):
                for j in range(6, 12):
                    r = b.submit(q[8 * j: 8 * (j + 1)], 8)
                    r.result(120)
                    drill_reqs.append(r)
            assert sentinel.drain(60)
            est_bad = sentinel.estimate("cagra")
            # a MEASURABLE serve.recall drop, visible in the gauge too
            assert est_bad < 0.6 and est_good - est_bad >= 0.2, \
                (est_good, est_bad)
            assert reg.snapshot()["gauges"]["serve.recall.cagra"] \
                == pytest.approx(est_bad)

            # trace-stamped recall_regression: the crossing sample's
            # trace ID belongs to one of the drill requests
            evs = events.recent(kind="recall_regression")
            assert len(evs) == 1
            assert evs[0]["site"] == "serve.recall.cagra"
            assert evs[0]["trace_id"] in {r.trace_id for r in drill_reqs}
            assert evs[0]["estimate"] < 0.7
            # the injected fault is on the record (and did NOT demote)
            assert any(e["site"] == "drill.cagra.search"
                       for e in events.recent(kind="fault_injected"))
            assert "drill.cagra.search" not in guarded.demoted_sites()

            # SLO breach verdict in the debugz snapshot, end to end
            snap = debugz.snapshot(batcher=b, registry=reg, slo=eng)
            assert snap["slo"]["verdict"] == "breach"
            assert snap["slo"]["targets"]["recall"]["verdict"] == "breach"
            assert snap["health"]["drill_cagra"]["family"] == "cagra"
            assert "int8" in snap["health"]["drill_cagra"]["quant"]
            qsec = {s2["name"]: s2 for s2 in snap["quality"]}
            assert qsec["serve"]["families"]["cagra"]["below_floor"]
            assert any(e["kind"] == "slo_breach" for e in snap["events"])
            json.dumps(snap, allow_nan=False)
            text = debugz.render_text(batcher=b, registry=reg, slo=eng)
            assert "BELOW FLOOR" in text and "recall: breach" in text
        finally:
            b.close()
            sentinel.close()
            quality.unwatch_index("drill_cagra")


class TestEngineLadderDrift:
    """ISSUE 12 engine drift guard: every traversal engine registered on
    a family (cagra.ENGINES) must (a) be in the family's DEFAULT
    tune_search race and (b) be pre-compilable through serve/warmup.py's
    ladder sweep — a new engine without a measured race lane or a
    warmup path would be an unraceable, first-request-compiled static.
    The source sweep keeps the registry itself honest: every concrete
    ``engine ==``/``_go("...")`` static in cagra must be a registered
    member."""

    def test_engine_statics_are_registered(self):
        import raft_tpu
        from raft_tpu.neighbors import cagra

        src = (pathlib.Path(raft_tpu.__file__).parent / "neighbors"
               / "cagra.py").read_text()
        # the traversal dispatch statics: search()'s _go("<engine>")
        # branches plus every comparison against the resolved `eng`
        # (build_knn_graph's brute-pass engines are a different knob)
        statics = set(re.findall(r'_go\("(\w+)"\)', src))
        eng_cmp = set(re.findall(r'\beng\s*==\s*"(\w+)"', src))
        eng_cmp |= {m for grp in re.findall(r'\beng\s+in\s+\(([^)]*)\)',
                                            src)
                    for m in re.findall(r'"(\w+)"', grp)}
        assert statics == set(cagra.ENGINES), (
            f"dispatch statics {sorted(statics)} drifted from "
            f"cagra.ENGINES {sorted(cagra.ENGINES)} — register the "
            "engine (race + warmup coverage) or remove the static")
        assert eng_cmp - {"auto"} <= set(cagra.ENGINES), (
            f"unregistered engine comparisons: "
            f"{sorted(eng_cmp - {'auto'} - set(cagra.ENGINES))}")

    def test_default_race_covers_every_engine(self, tmp_path, rng,
                                              monkeypatch):
        """tune_search's DEFAULT candidate set == cagra.ENGINES (the
        race is captured, not run — the real three-way race is
        test_cagra_fused.py's slow lane)."""
        from raft_tpu.neighbors import cagra
        from raft_tpu.ops import autotune

        seen = {}

        def fake_tune_best(key, cands, *a, **kw):
            seen["cands"] = set(cands)
            return "gather", {c: 0.0 for c in cands}

        monkeypatch.setattr(autotune, "tune_best", fake_tune_best)
        data = rng.normal(size=(256, 8)).astype(np.float32)
        from raft_tpu.neighbors import cagra as _cg
        ix = _cg.build(data, _cg.IndexParams(
            intermediate_graph_degree=12, graph_degree=8, seed=0))
        _cg.tune_search(ix, data[:8], 4, _cg.SearchParams(
            itopk_size=16, search_width=1, max_iterations=1))
        assert seen["cands"] == set(cagra.ENGINES)

    def test_warmup_sweeps_engine_ladder(self, reg_or_none=None):
        """serve/warmup.py warms an ``engines`` mapping across the FULL
        ladder (shape count = engines × ladder shapes), labeling each
        engine's compiles — the plumbing that pre-compiles the fused
        engine at serving buckets instead of on the first request. The
        real cagra-closure zero-recompile assertion rides the slow lane
        below."""
        from raft_tpu.neighbors import cagra
        from raft_tpu.serve import warmup as warmup_mod

        calls = []

        def mk(tag):
            def fn(q, k):
                calls.append((tag, q.shape[0], k))
                return (np.zeros((q.shape[0], k), np.float32),
                        np.zeros((q.shape[0], k), np.int32))
            return fn

        ladder = BucketLadder((4, 8), (4,))
        reg = metrics.Registry()
        warmup_mod.warmup(None, ladder, 8, registry=reg, name="drift",
                          engines={e: mk(e) for e in cagra.ENGINES})
        want = {(e, mb, 4) for e in cagra.ENGINES for mb in (4, 8)}
        assert set(calls) == want
        assert reg.gauge("drift.warmup.shapes").value == len(want)

    @pytest.mark.slow
    def test_every_engine_precompiled_at_serving_buckets(self, rng):
        """Functional form: after an engines-ladder warmup of REAL cagra
        closures, a request on ANY engine at a ladder shape triggers
        zero XLA compilations — the fused megakernel included."""
        from raft_tpu.neighbors import cagra
        from raft_tpu.serve import warmup as warmup_mod
        from raft_tpu.serve.warmup import count_compilations

        data = rng.normal(size=(512, 8)).astype(np.float32)
        ix = cagra.build(data, cagra.IndexParams(
            intermediate_graph_degree=12, graph_degree=8, seed=0))
        sp = cagra.SearchParams(itopk_size=16, search_width=1,
                                max_iterations=2, candidate_dtype="int8")
        fns = {e: cagra.make_searcher(ix, sp, engine=e)
               for e in cagra.ENGINES}
        ladder = BucketLadder((8,), (4,))
        warmup_mod.warmup(None, ladder, 8, registry=metrics.Registry(),
                          name="drift2", engines=fns)
        q = np.zeros((8, 8), np.float32)
        with count_compilations() as cc:
            for fn in fns.values():
                out = fn(q, 4)
                import jax as _jax
                _jax.block_until_ready(out)
        assert cc.count == 0, (
            f"{cc.count} first-request compiles after the engine-ladder "
            "warmup")
