"""Soak harness tests: the time-compressed chaos drill (ISSUE 16).

Four layers:

* the PR's foundation satellites (events drain/sink, debugz hook-error
  latches, MTTR histograms, Scenario.stages);
* the soak building blocks (SimClock, ShadowCorpus oracle, seeded
  workload, ChaosPlan);
* the composed tier-1 smoke: every chaos stage, every MTTR arc, zero
  invariant violations, deterministic per seed;
* the merge-flip × Tenant.swap race (satellite: both paths bump the
  generations the query cache keys on — no stale hit, no lost ack).

The full-length drill rides the slow lane behind
``RAFT_TPU_SOAK_SECONDS`` (simulated seconds, e.g. 600) — same
harness, longer clock.
"""
import json
import os

import numpy as np
import pytest

from raft_tpu.core import events, faults
from raft_tpu.neighbors import mutable
from raft_tpu.ops import guarded
from raft_tpu.parallel import sharded_ann
from raft_tpu.serve import debugz, metrics
from raft_tpu.serve.qcache import QueryCache
from raft_tpu.serve.tenancy import ServeFabric
from raft_tpu.soak import (ChaosPlan, ShadowCorpus, SimClock, SoakConfig,
                           SoakHarness, TenantLoad, WorkloadGen, run_soak,
                           standard_plan)

pytestmark = [pytest.mark.soak, pytest.mark.serve]


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    events.clear()
    guarded.reset()
    monkeypatch.setenv("RAFT_TPU_AUTOTUNE_CACHE", "")
    yield
    events.detach_sink()
    guarded.reset()


@pytest.fixture
def clock():
    return SimClock()


# ---------------------------------------------------------------------------
# foundation satellites
# ---------------------------------------------------------------------------
class TestEventsIncremental:
    def test_drain_new_cursor(self):
        events.record("upsert", "t.a")
        items, cur = events.drain_new(0)
        assert [e["site"] for e in items][-1] == "t.a"
        again, cur2 = events.drain_new(cur)
        assert again == [] and cur2 == cur
        events.record("delete", "t.b")
        fresh, cur3 = events.drain_new(cur)
        assert [e["site"] for e in fresh] == ["t.b"] and cur3 == cur + 1

    def test_attach_sink_streams_jsonl(self, tmp_path):
        p = tmp_path / "ev.jsonl"
        events.attach_sink(str(p))
        events.record("upsert", "t.sink", rows=3)
        events.detach_sink()
        events.record("upsert", "t.after")   # must NOT land in the file
        lines = [json.loads(ln) for ln in p.read_text().splitlines()]
        assert [e["site"] for e in lines] == ["t.sink"]
        assert lines[0]["rows"] == 3

    def test_attach_sink_include_ring_prologue(self, tmp_path):
        events.record("upsert", "t.before")
        p = tmp_path / "ev.jsonl"
        events.attach_sink(str(p), include_ring=True)
        events.detach_sink()
        sites = [json.loads(ln)["site"] for ln in p.read_text().splitlines()]
        assert "t.before" in sites


class TestHookErrorLatch:
    def test_counts_and_transition_events(self, tmp_path):
        reg = metrics.Registry()
        boom = {"on": True}

        def flaky_hook():
            if boom["on"]:
                raise RuntimeError("dead maintenance hook")

        # hooks are named by __qualname__ (harness hooks set it); a
        # test-local closure needs the same grooming
        flaky_hook.__qualname__ = "flaky_hook"
        w = debugz.SnapshotWriter(str(tmp_path / "z.json"), registry=reg,
                                  hooks=[flaky_hook])
        w.tick()
        w.tick()
        c = reg.counter("debugz.hook_errors.flaky_hook").value
        assert c == 2            # counted per tick...
        evs = [e for e in events.recent(kind="hook_error")]
        assert len(evs) == 1     # ...flight-recorded once per transition
        assert evs[0]["action"] == "failed"
        boom["on"] = False
        w.tick()
        evs = [e for e in events.recent(kind="hook_error")]
        assert [e["action"] for e in evs] == ["failed", "recovered"]

    def test_injected_crash_propagates(self, tmp_path):
        """InjectedCrash is process death — the latch must NOT absorb
        it (the soak harness owns crash recovery attribution)."""
        def dying_hook():
            raise faults.InjectedCrash("crash_point", "t.hook")

        w = debugz.SnapshotWriter(str(tmp_path / "z.json"),
                                  registry=metrics.Registry(),
                                  hooks=[dying_hook])
        with pytest.raises(faults.InjectedCrash):
            w.tick()


class TestMttrMetrics:
    def test_buckets_cover_recovery_scales(self):
        assert metrics.MTTR_BUCKETS_S[-1] >= 3600.0
        assert metrics.MTTR_BUCKETS_S[0] <= 0.5
        assert max(metrics.LATENCY_BUCKETS_S) < 30.0  # why MTTR needs its own

    def test_heal_mttr_observed_on_breaker_close(self, monkeypatch):
        if any(f.kind in ("kernel_compile", "kernel_fault")
               for f in faults.active()):
            pytest.skip("ambient kernel faults keep the probe failing")
        now = {"t": 0.0}
        monkeypatch.setattr(guarded, "_clock", lambda: now["t"])
        h = metrics.histogram("heal.mttr.select_k.kpass",
                              metrics.MTTR_BUCKETS_S)
        c0, s0 = h.count, h.sum

        def boom():
            raise RuntimeError("soak mttr drill")

        assert guarded.guarded_call("select_k.kpass", boom,
                                    lambda: "fb") == "fb"
        now["t"] = 45.0           # past the 30s probation
        assert guarded.guarded_call("select_k.kpass", lambda: "ok",
                                    lambda: "fb") == "ok"
        assert h.count == c0 + 1
        assert abs((h.sum - s0) - 45.0) < 0.01

    def test_shard_mttr_observed_on_restore(self, monkeypatch):
        import jax
        from jax.sharding import Mesh

        now = {"t": 100.0}
        monkeypatch.setattr(sharded_ann, "_clock", lambda: now["t"])
        devs = jax.devices()
        mesh = Mesh(np.array((devs * 2)[:2]), ("shard",))
        data = np.zeros((2, 4, 4), np.float32)
        graphs = np.zeros((2, 4, 2), np.int32)
        idx = sharded_ann.ShardedCagra(
            mesh, data, graphs, np.array([0, 2]), np.array([2, 2]),
            n_total=4, metric=sharded_ann.DistanceType.L2Expanded)
        h = metrics.histogram("shard.mttr", metrics.MTTR_BUCKETS_S)
        c0, s0 = h.count, h.sum
        idx.mark_shard_failed(1)
        now["t"] = 117.5
        idx.mark_shard_failed(1, ok=True)
        assert h.count == c0 + 1
        assert abs((h.sum - s0) - 17.5) < 0.01

    def test_scenario_stages_json_view(self, clock):
        sc = faults.Scenario(clock=clock)
        sc.add("kernel_fault", "soak.serve", at_s=5.0, until_s=9.0)
        sc.add("crash_point", "mutable.merge.pre_flip", at_s=1.0, count=1)
        view = sc.stages()
        assert [s["kind"] for s in view] == ["kernel_fault", "crash_point"]
        assert view[0]["until_s"] == 9.0 and view[1]["count"] == 1
        json.dumps(view)          # strictly serializable


# ---------------------------------------------------------------------------
# building blocks
# ---------------------------------------------------------------------------
class TestWorkload:
    def test_sim_clock_monotonic(self, clock):
        assert clock() == 0.0
        clock.advance(2.5)
        assert clock.now == 2.5
        with pytest.raises(ValueError):
            clock.advance(-1.0)

    def test_shadow_corpus_oracle(self, rng):
        o = ShadowCorpus(4)
        vecs = rng.standard_normal((6, 4)).astype(np.float32)
        o.apply_upsert(range(6), vecs)
        assert o.size == 6
        assert o.apply_delete([2, 99]) == 1
        assert o.size == 5 and 2 not in o.ids()
        # exact top-1 of a stored vector is itself
        got = o.true_knn(vecs[3][None, :], 1)
        assert int(got[0, 0]) == 3
        # short-of-k pads with -1
        got = o.true_knn(vecs[:1], 8)
        assert (got[0, 5:] == -1).all()
        assert o.recall_of(vecs[:2], o.true_knn(vecs[:2], 3), 3) == 1.0

    def test_workload_deterministic_per_seed(self):
        spec = [TenantLoad("a", upserts_per_tick=2, deletes_per_tick=1),
                TenantLoad("b", query_pool=4)]

        def stream(seed):
            wl = WorkloadGen(seed, 8, spec)
            oracles = {}
            for t in spec:
                ids, vecs = wl.initial_corpus(t.name, 32)
                oracles[t.name] = ShadowCorpus(8)
                oracles[t.name].apply_upsert(ids, vecs)
            out = []
            for _ in range(5):
                out.append([(q.tenant, q.queries.tobytes())
                            for q in wl.queries_for_tick()])
                for m in wl.mutations_for_tick(oracles):
                    out.append((m.tenant, m.kind, m.ids))
                    if m.kind == "upsert":
                        oracles[m.tenant].apply_upsert(m.ids, m.vectors)
                    else:
                        oracles[m.tenant].apply_delete(m.ids)
            return out

        assert stream(3) == stream(3)
        assert stream(3) != stream(4)


class TestChaosPlan:
    def test_actions_fire_once_and_window(self, clock):
        plan = ChaosPlan(clock)
        plan.add_action("swap", 5.0, tenant="cold")
        plan.add_action("overload", 3.0, 7.0, extra=10)
        assert plan.due_instants() == [] and plan.active("overload") == []
        clock.advance(4.0)
        assert [a.payload["extra"] for a in plan.active("overload")] == [10]
        assert plan.due_instants() == []
        clock.advance(2.0)          # t=6: swap due, overload still active
        assert [a.name for a in plan.due_instants()] == ["swap"]
        assert plan.due_instants() == []      # fires once
        clock.advance(2.0)          # t=8: window closed
        assert plan.active("overload") == []

    def test_standard_plan_composition(self, clock):
        plan = standard_plan(clock, t0=10.0, window=10.0)
        kinds = plan.fault_kinds()
        assert {"kernel_fault", "io_error", "wal_torn_tail",
                "crash_point", "shard_dead"} == set(kinds)
        desc = plan.describe()
        assert len(desc["actions"]) == 2
        json.dumps(desc)

    def test_describe_is_deterministic(self):
        c1, c2 = SimClock(), SimClock()
        assert standard_plan(c1).describe() == standard_plan(c2).describe()


# ---------------------------------------------------------------------------
# the composed drill
# ---------------------------------------------------------------------------
def _skip_under_ambient_faults():
    if any(f.kind in ("kernel_compile", "kernel_fault")
           for f in faults.active()):
        pytest.skip("ambient kernel faults would double-arm the "
                    "soak's own chaos plan")


class TestSoakSmoke:
    def test_smoke_composition_zero_violations(self, tmp_path):
        """The tier-1 acceptance drill: mutation + merge + swap + shard
        death + kernel fault + WAL tear + io errors + overload under
        Zipfian multi-tenant load, zero invariant violations, finite
        MTTR for every injected fault kind."""
        _skip_under_ambient_faults()
        art = run_soak(SoakConfig.smoke(seed=7),
                       workdir=str(tmp_path / "soak"))
        assert art["verdict"] == "PASS"
        assert art["violations"] == []
        json.dumps(art, allow_nan=False)      # the artifact is strict JSON
        # every fault kind the plan armed recovered in finite sim time
        for kind, rec in art["mttr"].items():
            assert rec["count"] >= 1, f"{kind} never completed an MTTR arc"
            assert rec["mean_s"] is not None and rec["mean_s"] > 0.0
        # phase timeline is annotated and contiguous
        names = [p["name"] for p in art["phases"]]
        assert names[0] == "warmup" and names[-1] == "quiesce"
        assert "chaos" in names and "recovery" in names
        for a, b in zip(art["phases"], art["phases"][1:]):
            assert a["t1_s"] == b["t0_s"]
        # composition really happened: traffic served on every tenant,
        # sheds only on the overloaded one, cache hits on the cold one,
        # swaps/recoveries bumped generations
        tn = art["tenants"]
        assert all(v["served"] > 0 for v in tn.values())
        assert tn["hot"]["shed"] > 0 and tn["cold"]["shed"] == 0
        assert tn["cold"]["qcache_hits"] > 0
        assert tn["cold"]["generation"] >= 1      # scheduled live swap
        assert tn["hot"]["generation"] >= 1       # crash recovery swap
        # events streamed incrementally to the sink
        sink = (tmp_path / "soak" / "events.jsonl").read_text().splitlines()
        kinds = {json.loads(ln)["kind"] for ln in sink}
        assert {"soak_phase", "merge_committed", "tenant_swap",
                "breaker_open", "breaker_close", "wal_recovered",
                "shard_restored", "brownout"} <= kinds

    def test_same_seed_same_verdict(self, tmp_path):
        """Determinism: two same-seed runs produce the same chaos
        schedule, timeline, and verdict — the artifact dicts are
        equal."""
        _skip_under_ambient_faults()
        # a short run: the full fault arcs live in the smoke test; this
        # one only has to prove schedule/verdict determinism cheaply
        cfg = SoakConfig(seed=11, duration_s=24.0, chaos_t0=8.0,
                         chaos_window=10.0)
        a = run_soak(cfg, workdir=str(tmp_path / "a"))
        b = run_soak(cfg, workdir=str(tmp_path / "b"))
        assert a == b
        # and a different seed genuinely changes the run
        cfg2 = SoakConfig(seed=12, duration_s=24.0, chaos_t0=8.0,
                          chaos_window=10.0)
        c = run_soak(cfg2, workdir=str(tmp_path / "c"))
        assert c["tenants"] != a["tenants"]

    @pytest.mark.slow
    def test_full_drill(self, tmp_path):
        """The long soak: RAFT_TPU_SOAK_SECONDS simulated seconds
        (default 600) of the same composed drill."""
        _skip_under_ambient_faults()
        sim_s = float(os.environ.get("RAFT_TPU_SOAK_SECONDS", "600"))
        art = run_soak(SoakConfig(seed=7, duration_s=sim_s),
                       workdir=str(tmp_path / "soak_full"))
        assert art["verdict"] == "PASS", art["violations"][:5]
        for kind, rec in art["mttr"].items():
            assert rec["count"] >= 1 and rec["mean_s"] is not None


# ---------------------------------------------------------------------------
# merge flip × Tenant.swap race (satellite)
# ---------------------------------------------------------------------------
class TestMergeSwapRace:
    """Both a mutable merge flip and a Tenant.swap bump generations the
    query cache keys on (``sig|g<gen>|m<merge_gen>``). Racing them on
    one tenant must never serve a stale cached block nor lose an acked
    write — including when the merge dies at a crash point mid-race."""

    def _fabric_with(self, idx, clock):
        fab = ServeFabric(idx.dim, cache=QueryCache(capacity=64),
                          name="race", clock=clock, autostart=False)
        fab.add_tenant("t", index=idx)
        return fab

    def _serve(self, fab, q, k=4):
        req = fab.submit("t", q, k)
        while fab.drain_once():
            pass
        assert req.done()
        return req.result(timeout=5)

    def test_flip_racing_swap_no_stale_hit_no_lost_ack(self, tmp_path,
                                                       rng, clock):
        X = rng.standard_normal((96, 8)).astype(np.float32)
        idx = mutable.create(tmp_path / "i", X)
        idx._clock = clock
        fab = self._fabric_with(idx, clock)
        tenant = fab.tenant("t")
        q = X[11:12].copy()
        first = self._serve(fab, q)
        assert 11 in np.asarray(first.indices)[0]
        hit0 = tenant._hits.value
        assert self._serve(fab, q) is not None
        assert tenant._hits.value == hit0 + 1     # exact repeat hits
        new_vec = rng.standard_normal((1, 8)).astype(np.float32)

        def racing_swap():
            # mid-merge (after the snapshot watermark): an acked write,
            # a truth-changing delete, and a concurrent swap that bumps
            # the tenant generation while the flip is in flight
            idx.upsert(np.array([500]), new_vec)
            idx.delete([11])
            tenant.swap(search_fn=mutable.make_searcher(idx), warm=False)

        idx._after_snapshot_hook = racing_swap
        try:
            assert idx.merge() == "committed"
        finally:
            idx._after_snapshot_hook = None
        hits_before = tenant._hits.value
        res = self._serve(fab, q)
        # no stale hit: both generation bumps invalidated the entry
        assert tenant._hits.value == hits_before
        got = np.asarray(res.indices)[0]
        assert 11 not in got                      # the delete serves
        res2 = self._serve(fab, new_vec)
        assert 500 in np.asarray(res2.indices)[0]  # the acked write serves

    @pytest.mark.parametrize("crash_site", ["mutable.merge.pre_flip",
                                            "mutable.merge.post_flip"])
    def test_crashed_flip_racing_swap_recovers_acked_writes(
            self, tmp_path, rng, clock, crash_site):
        if faults.active():
            pytest.skip("ambient faults would interleave with the "
                        "armed crash point")
        X = rng.standard_normal((96, 8)).astype(np.float32)
        p = tmp_path / "i"
        idx = mutable.create(p, X)
        idx._clock = clock
        fab = self._fabric_with(idx, clock)
        tenant = fab.tenant("t")
        q = X[11:12].copy()
        assert 11 in np.asarray(self._serve(fab, q).indices)[0]
        new_vec = rng.standard_normal((1, 8)).astype(np.float32)

        def racing_swap():
            idx.upsert(np.array([500]), new_vec)   # acked before the crash
            idx.delete([11])
            tenant.swap(search_fn=mutable.make_searcher(idx), warm=False)

        idx._after_snapshot_hook = racing_swap
        try:
            with faults.inject("crash_point", crash_site, count=1):
                with pytest.raises(faults.InjectedCrash):
                    idx.merge()
        finally:
            idx._after_snapshot_hook = None
        # simulated restart: recover from disk, swap into the tenant
        rec = mutable.recover(p)
        rec._clock = clock
        tenant.swap(new_index=rec, warm=False)
        hits_before = tenant._hits.value
        res = self._serve(fab, q)
        assert tenant._hits.value == hits_before   # no stale block served
        assert 11 not in np.asarray(res.indices)[0]
        res2 = self._serve(fab, new_vec)
        assert 500 in np.asarray(res2.indices)[0]  # acked write survived
