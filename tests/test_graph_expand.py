"""Gather-free CAGRA traversal (ISSUE 4): recall parity of the
edge-resident candidate store + Pallas frontier-expansion kernel
(``engine="edge"``) against the XLA gather path, plus the store's cache
contract (idempotent prepare, pytree travel, guarded fallback).

Tier-1 cost discipline: ONE shared geometry (module-scoped index, the
same SearchParams across parity tests so cached executables reuse), an
explicit ``max_iterations`` cap (interpret-mode hop cost scales with the
hop count), and ``itopk=32 > 16`` so the kernel's extraction compiles as
a fori_loop, not 32 unrolled passes."""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ann_utils import calc_recall, naive_knn
from raft_tpu.core import faults
from raft_tpu.core.bitset import Bitset
from raft_tpu.neighbors import cagra
from raft_tpu.ops import autotune
from raft_tpu.ops.graph_expand import graph_expand

N, D, DEG, M, K = 2000, 32, 32, 64, 10
# bf16 candidate_dtype (default) for the gather twin of the bf16 store;
# int8 twin for the int8 store — "equal params" per engine pair
SP = cagra.SearchParams(itopk_size=32, search_width=4, max_iterations=5)
SP8 = dataclasses.replace(SP, candidate_dtype="int8")


@pytest.fixture(scope="module")
def dataset():
    rng = np.random.default_rng(11)
    return rng.standard_normal((N, D)).astype(np.float32)


@pytest.fixture(scope="module")
def queries():
    rng = np.random.default_rng(12)
    return rng.standard_normal((M, D)).astype(np.float32)


@pytest.fixture(scope="module")
def oracle(dataset, queries):
    return naive_knn(dataset, queries, K)[1]


@pytest.fixture(scope="module")
def index(dataset):
    ix = cagra.build(dataset, cagra.IndexParams(
        intermediate_graph_degree=48, graph_degree=DEG, seed=0))
    cagra.prepare_traversal(ix)            # int8 edge store (the default)
    return ix


def _copy(ix):
    """Fresh Index object sharing the same arrays — store experiments
    must not mutate the module fixture's caches."""
    return cagra.Index(ix.dataset, ix.graph, ix.metric, ix.seed_nodes)


class TestGraphExpandKernel:
    @pytest.mark.parametrize("store", ["int8", "bfloat16"])
    def test_matches_reference(self, store):
        """Direct kernel check vs a numpy reference for both storage
        dtypes: exact edge positions, distances to fp tolerance (k<=16
        unrolled path; the search tests cover the fori_loop path)."""
        rng = np.random.default_rng(0)
        n, deg, d, m, w, kout = 150, 16, 20, 11, 2, 8
        deg_p, dim_p = 32, 128
        data = rng.standard_normal((n, d)).astype(np.float32)
        graph = rng.integers(0, n, (n, deg)).astype(np.int32)
        aux = np.zeros((n, 2, deg_p), np.float32)
        if store == "int8":
            scale = np.maximum(np.abs(data).max(1), 1e-30) / 127.0
            q8 = np.clip(np.round(data / scale[:, None]), -127, 127)
            deq = q8.astype(np.float32) * scale[:, None]
            ev = np.zeros((n, deg_p, dim_p), np.int8)
            ev[:, :deg, :d] = q8[graph]
            aux[:, 0, :deg] = scale[graph]
        else:
            import ml_dtypes

            deq = data.astype(ml_dtypes.bfloat16).astype(np.float32)
            ev = np.zeros((n, deg_p, dim_p), ml_dtypes.bfloat16)
            ev[:, :deg, :d] = deq[graph].astype(ml_dtypes.bfloat16)
            aux[:, 0, :deg] = 1.0
        aux[:, 1, :deg] = (deq ** 2).sum(1)[graph]
        queries = rng.standard_normal((m, d)).astype(np.float32)
        parents = rng.integers(0, n, (m, w)).astype(np.int32)
        vals, epos = graph_expand(jnp.asarray(parents),
                                  jnp.asarray(queries), jnp.asarray(ev),
                                  jnp.asarray(aux), kout, degree=deg)
        ref = ((queries[:, None, None, :]
                - deq[graph[parents]]) ** 2).sum(-1)     # (m, w, deg)
        order = np.argsort(ref, axis=2, kind="stable")[:, :, :kout]
        vals, epos = np.asarray(vals), np.asarray(epos)
        if store == "int8":
            # int8 scores f32-highest in-kernel: positions are exact
            np.testing.assert_array_equal(epos, order)
            atol = 1e-4
        else:
            # the kernel's dot rounds q to bf16 (as the gather path
            # does), so near-ties may swap vs the f32 reference —
            # assert value-consistency instead of positional equality
            atol = 5e-2
        np.testing.assert_allclose(
            vals, np.take_along_axis(ref, epos, axis=2), atol=atol)
        np.testing.assert_allclose(
            vals, np.take_along_axis(ref, order, axis=2), atol=atol)


class TestEdgeEngine:
    def test_recall_parity_int8(self, index, queries, oracle):
        _, ig = cagra.search(index, queries, K, SP8, engine="gather")
        _, ie = cagra.search(index, queries, K, SP8, engine="edge")
        rg = calc_recall(np.asarray(ig), oracle)
        re = calc_recall(np.asarray(ie), oracle)
        assert re >= 0.85, re
        assert abs(re - rg) <= 0.002, (re, rg)

    @pytest.mark.slow
    def test_recall_parity_bf16(self, index, queries, oracle):
        """Full-search bf16-store parity (the bf16 kernel math itself is
        tier-1-covered by the direct reference test above)."""
        ix = _copy(index)
        cagra.prepare_traversal(ix, "bfloat16")
        assert ix._edge_store[0][0] == "bfloat16"
        _, ig = cagra.search(ix, queries, K, SP, engine="gather")
        _, ie = cagra.search(ix, queries, K, SP, engine="edge")
        rg = calc_recall(np.asarray(ig), oracle)
        re = calc_recall(np.asarray(ie), oracle)
        assert re >= 0.85, re
        assert abs(re - rg) <= 0.002, (re, rg)

    def test_recall_width1(self, index, queries, oracle):
        """width=1: one parent per hop exercises the kernel's
        query-routing degenerate case — a routing bug craters recall."""
        sp = dataclasses.replace(SP8, search_width=1, max_iterations=10)
        _, ie = cagra.search(index, queries, K, sp, engine="edge")
        assert calc_recall(np.asarray(ie), oracle) >= 0.85

    def test_merge_shrink_kprime(self, index, queries, oracle):
        """itopk < degree engages the per-parent top-k' truncation (the
        merge-width shrink); a candidate beyond a parent's k' best can
        in principle be lost, so the bound vs the equal-params gather
        run is looser than parity."""
        sp = dataclasses.replace(SP8, itopk_size=16, max_iterations=5)
        _, ig = cagra.search(index, queries, K, sp, engine="gather")
        _, ie = cagra.search(index, queries, K, sp, engine="edge")
        rg = calc_recall(np.asarray(ig), oracle)
        re = calc_recall(np.asarray(ie), oracle)
        assert re >= rg - 0.02, (re, rg)

    def test_filter_excluded_never_returned(self, index, dataset, queries):
        _, base = naive_knn(dataset, queries, 1)
        mask = np.ones(N, bool)
        mask[base[:, 0]] = False
        filt = Bitset.from_mask(jnp.asarray(mask))
        _, idx = cagra.search(index, queries, K, SP8, filter=filt,
                              engine="edge")
        got = np.asarray(idx)
        assert all(base[i, 0] not in got[i] for i in range(len(got)))

    def test_off_tile_degree(self, dataset, queries, oracle):
        """degree=24 is off the int8 sublane tile (deg_p pads to 32):
        pad edges must stay masked — a leak returns junk ids or junk
        (zero-vector) scores and craters recall."""
        ix = cagra.build(dataset[:1200], cagra.IndexParams(
            intermediate_graph_degree=32, graph_degree=24, seed=0))
        cagra.prepare_traversal(ix)
        assert ix._edge_store[1].shape[1] == 32    # padded sublane tile
        _, ie = cagra.search(ix, queries, K, SP8, engine="edge")
        got = np.asarray(ie)
        assert got[got >= 0].max() < 1200
        _, want = naive_knn(dataset[:1200], queries, K)
        assert calc_recall(got, want) >= 0.85

    @pytest.mark.faults
    def test_guarded_fallback_bit_identical(self, index, queries):
        """A frontier-kernel failure must serve the exact XLA gather
        results (bit-identical, distances included) and — being an
        injected fault — must not demote the site."""
        from raft_tpu.ops import guarded

        dg, ig = cagra.search(index, queries, K, SP8, engine="gather")
        with faults.inject("kernel_compile", "cagra.graph_expand"):
            df, if_ = cagra.search(index, queries, K, SP8, engine="edge")
        np.testing.assert_array_equal(np.asarray(if_), np.asarray(ig))
        np.testing.assert_array_equal(np.asarray(df), np.asarray(dg))
        assert "cagra.graph_expand" not in guarded.demoted_sites()


class TestEdgeStoreContract:
    def test_prepare_idempotent_no_double_alloc(self, index):
        """A second prepare on matching geometry is a no-op: the SAME
        arrays stay attached (no HBM double-alloc)."""
        ev0, aux0 = index._edge_store[1], index._edge_store[2]
        cagra.prepare_traversal(index)
        assert index._edge_store[1] is ev0
        assert index._edge_store[2] is aux0

    def test_store_travels_pytree_jit_arg(self, index, queries):
        """The store rides the Index pytree so jitted functions take the
        index as an ARGUMENT; jit results match eager."""
        leaves, td = jax.tree_util.tree_flatten(index)
        rebuilt = jax.tree_util.tree_unflatten(td, leaves)
        assert rebuilt._edge_store[0] == index._edge_store[0]
        qs = queries[:16]      # small grid: the outer jit re-traces all
        fn = jax.jit(lambda q, ix: cagra.search(ix, q, K, SP8,
                                                engine="edge"))
        _, i_jit = fn(qs, rebuilt)
        _, i_eager = cagra.search(index, qs, K, SP8, engine="edge")
        np.testing.assert_array_equal(np.asarray(i_jit),
                                      np.asarray(i_eager))

    def test_edge_requires_store_before_trace(self, index, queries):
        """engine='edge' on a storeless index under jit must fail loudly
        (the store cannot be built from inside a trace)."""
        from raft_tpu.core.errors import RaftError

        bare = _copy(index)
        fn = jax.jit(lambda q, ix: cagra.search(ix, q, K, SP8,
                                                engine="edge"))
        with pytest.raises(RaftError, match="prepare_traversal"):
            fn(queries, bare)

    def test_tune_search_race_and_store_policy(self, index, queries,
                                               monkeypatch):
        """tune_search measures the engines, records a dtype-aware
        bucket winner, and keeps the edge store only when a store-backed
        engine wins. The race is pinned to the gather/edge pair here for
        tier-1 cost (an interpret-mode fused lane is seconds of trace);
        the DEFAULT race covering all of cagra.ENGINES is held by the
        engine drift guard in test_quality.py and exercised for real in
        test_cagra_fused.py's slow lane."""
        monkeypatch.setenv("RAFT_TPU_AUTOTUNE_CACHE", "")  # no disk
        ix = _copy(index)
        sp = dataclasses.replace(SP8, max_iterations=2)
        qs = queries[:16]
        winner, timings = cagra.tune_search(ix, qs, K, sp, reps=2,
                                            engines=("gather", "edge"))
        assert winner in ("edge", "gather")
        assert set(timings) == {"edge", "gather"}
        store = getattr(ix, "_edge_store", None)
        assert (store is not None) == (winner == "edge")
        key = cagra._tune_key(ix, 16, K, sp,
                              store if store is not None
                              else (("int8",),))
        assert autotune.lookup(key) == winner
        autotune.forget(key)
