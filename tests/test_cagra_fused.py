"""One-dispatch CAGRA traversal (ISSUE 12): interpret-mode BIT-identity
of the fused megakernel (``engine="fused"``) against the per-hop edge
engine, the guarded fallback chain, and the structural one-dispatch
property (no device-side hop loop survives in the fused program).

Tier-1 cost discipline: ONE tiny geometry shared across the tier-1
tests (module-scoped index; the guarded and one-dispatch tests reuse
the parity test's cached executables/jaxprs), ``width=1`` +
``max_iterations=4`` keeps the interpret-mode megakernel trace small,
and the heavier corners (filters, k=1, off-tile degree + the k'
truncation, the fori-loop fold, bf16/IP, the real three-way race) ride
the ``slow`` lane per the tier-1 wall policy."""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu.core import faults
from raft_tpu.core.bitset import Bitset
from raft_tpu.neighbors import cagra
from raft_tpu.ops import cagra_fused, guarded

N, D, DEG, M, K = 800, 16, 16, 8, 5
SP = cagra.SearchParams(itopk_size=16, search_width=1, max_iterations=4,
                        candidate_dtype="int8")


@pytest.fixture(scope="module")
def dataset():
    rng = np.random.default_rng(21)
    return rng.standard_normal((N, D)).astype(np.float32)


@pytest.fixture(scope="module")
def queries():
    rng = np.random.default_rng(22)
    return rng.standard_normal((M, D)).astype(np.float32)


@pytest.fixture(scope="module")
def index(dataset):
    ix = cagra.build(dataset, cagra.IndexParams(
        intermediate_graph_degree=24, graph_degree=DEG, seed=0))
    cagra.prepare_traversal(ix)            # int8 edge store + graph rows
    return ix


def _parity(ix, qs, k, sp, filt=None):
    de, ie = cagra.search(ix, qs, k, sp, engine="edge", filter=filt)
    df, if_ = cagra.search(ix, qs, k, sp, engine="fused", filter=filt)
    np.testing.assert_array_equal(np.asarray(if_), np.asarray(ie))
    np.testing.assert_array_equal(np.asarray(df), np.asarray(de))
    return np.asarray(ie)


class TestFusedParity:
    def test_bit_identity_core(self, index, queries):
        """Same seeds, same store → the megakernel's whole traversal is
        bit-identical to the per-hop edge engine (ids AND distances):
        parent pick order, scoring, k' extraction, dedup and the
        positional fold all mirror the hop body exactly."""
        ids = _parity(index, queries, K, SP)
        assert (ids >= 0).all() and (ids < N).all()

    @pytest.mark.slow
    def test_bit_identity_k1_and_filter(self, index, dataset, queries):
        """k=1 boundary and the bitset filter (the in-kernel penalty
        rows): still bit-identical, and filtered rows never surface."""
        _parity(index, queries, 1, SP)
        mask = np.ones(N, bool)
        mask[::3] = False
        filt = Bitset.from_mask(jnp.asarray(mask))
        ids = _parity(index, queries, K, SP, filt=filt)
        assert not np.isin(ids[ids >= 0], np.where(~mask)[0]).any()

    @pytest.mark.slow
    def test_bit_identity_off_tile_kprime_width(self, dataset, queries):
        """degree=24 is off the int8 sublane tile (deg_p pads to 32) AND
        exceeds itopk=16, engaging the per-parent top-k' truncation;
        width=2 engages the cross-parent dedup and the multi-fold merge
        — the tie-heaviest corner of the parity argument."""
        ix = cagra.build(dataset, cagra.IndexParams(
            intermediate_graph_degree=32, graph_degree=24, seed=0))
        cagra.prepare_traversal(ix)
        sp = dataclasses.replace(SP, search_width=2, max_iterations=3)
        ids = _parity(ix, queries, K, sp)
        assert (ids[ids >= 0] < N).all()

    @pytest.mark.slow
    def test_bit_identity_fori_paths_bf16_ip(self, dataset, queries):
        """itopk=64 drives the fold through its fori_loop form (k>32)
        and kprime>16 drives the extraction loop; bf16 store + IP metric
        cover the other scoring branch."""
        ix = cagra.build(dataset, cagra.IndexParams(
            intermediate_graph_degree=24, graph_degree=DEG,
            metric="inner_product", seed=0))
        cagra.prepare_traversal(ix, "bfloat16")
        sp = cagra.SearchParams(itopk_size=64, search_width=2,
                                max_iterations=2)
        _parity(ix, queries, K, sp)

    @pytest.mark.slow
    def test_tune_search_races_fused(self, index, queries, monkeypatch):
        """The real three-way race: default engines include fused (when
        VMEM-capable), the winner is recorded, and the store policy
        follows store-backed winners."""
        from raft_tpu.ops import autotune

        monkeypatch.setenv("RAFT_TPU_AUTOTUNE_CACHE", "")
        ix = cagra.Index(index.dataset, index.graph, index.metric,
                         index.seed_nodes)
        sp = dataclasses.replace(SP, max_iterations=2)
        winner, timings = cagra.tune_search(ix, queries, K, sp, reps=2)
        assert set(timings) == set(cagra.ENGINES)
        assert winner in cagra.ENGINES
        store = getattr(ix, "_edge_store", None)
        assert (store is not None) == (winner in ("edge", "fused"))
        key = cagra._tune_key(ix, M, K, sp,
                              store if store is not None
                              else (("int8",),))
        assert autotune.lookup(key) == winner
        autotune.forget(key)


class TestFusedGuarded:
    @pytest.mark.faults
    def test_fallback_bit_identical_per_call(self, index, queries):
        """An injected kernel_compile at the fused site serves THIS call
        through the edge chain bit-identically and moves no breaker."""
        de, ie = cagra.search(index, queries, K, SP, engine="edge")
        with faults.inject("kernel_compile", "cagra.fused_search"):
            df, if_ = cagra.search(index, queries, K, SP, engine="fused")
        np.testing.assert_array_equal(np.asarray(if_), np.asarray(ie))
        np.testing.assert_array_equal(np.asarray(df), np.asarray(de))
        assert "cagra.fused_search" not in guarded.demoted_sites()

    @pytest.mark.faults
    def test_kernel_fault_opens_injected_breaker_serves_identical(
            self, index, queries):
        """kernel_fault drives the breaker (the persistent-failure
        drill): the faulted calls serve the edge results bit-identically
        and the open is flagged injected — never persisted, so it cannot
        outlive the armed fault (no sticky demotion)."""
        guarded.reset()
        de, ie = cagra.search(index, queries, K, SP, engine="edge")
        try:
            with faults.inject("kernel_fault", "cagra.fused_search"):
                df, if_ = cagra.search(index, queries, K, SP,
                                       engine="fused")
            np.testing.assert_array_equal(np.asarray(if_), np.asarray(ie))
            np.testing.assert_array_equal(np.asarray(df), np.asarray(de))
            snap = guarded.breaker_snapshot()["cagra.fused_search"]
            assert snap["state"] == "open"
            assert snap["injected"] is True
        finally:
            guarded.reset()


class TestServingClosure:
    def test_donated_closure_matches_plain(self, index, queries):
        """make_searcher(donate=True) serves identical results through
        its per-k donated jit cache (CPU ignores the donation itself —
        the contract under test is correctness + one cached executable
        per k, so serving buckets never retrace)."""
        plain = cagra.make_searcher(index, SP, donate=False,
                                    engine="gather")
        donated = cagra.make_searcher(index, SP, donate=True,
                                      engine="gather")
        dp, ip = plain(queries, K)
        dd, id_ = donated(queries, K)
        np.testing.assert_array_equal(np.asarray(id_), np.asarray(ip))
        np.testing.assert_array_equal(np.asarray(dd), np.asarray(dp))
        dd2, id2 = donated(queries, K)       # second call: cached jit
        np.testing.assert_array_equal(np.asarray(id2), np.asarray(ip))


class TestOneDispatch:
    def test_fused_program_has_no_hop_loop(self, index, queries):
        """The acceptance property, structurally: the fused search's
        jaxpr contains ZERO device-side while loops (each iteration of
        one is a separate kernel launch on device) and the megakernel
        launch site; the edge engine's program keeps its hop loop."""
        stats = cagra_fused.one_dispatch_stats(
            lambda q, ix: cagra.search(ix, q, K, SP, engine="fused"),
            jnp.asarray(queries), index)
        assert stats["one_dispatch"], stats
        assert stats["while_loops"] == 0
        assert stats["pallas_calls"] >= 1
        edge = cagra_fused.one_dispatch_stats(
            lambda q, ix: cagra.search(ix, q, K, SP, engine="edge"),
            jnp.asarray(queries), index)
        assert edge["while_loops"] >= 1
        assert not edge["one_dispatch"]
