"""RAFT-native index file interop (core/raft_format.py): round-trips
through the reference's npy-frame serialization layout
(detail/ivf_pq_serialize.cuh, ivf_flat_serialize.cuh, cagra_serialize.cuh)
and unit checks of the interleaved bitfield codecs.

``TestReferenceWireFormat`` holds BYTE-LEVEL goldens: an independent
in-test writer reproduces the C++ serializer's exact byte stream
(write_header, mdspan_numpy_serializer.hpp:316-341: magic, 1.0 version,
le16 HEADER_LEN, dict WITHOUT numpy's trailing ", ", 64-byte space
padding + newline) so round-trips cannot self-validate a wrong layout —
the r4 advisor found exactly that failure mode."""
import ast
import io
import struct

import jax.numpy as jnp
import numpy as np
import pytest

from ann_utils import calc_recall, naive_knn
from raft_tpu.core import raft_format as rf
from raft_tpu.neighbors import cagra, ivf_flat, ivf_pq


@pytest.fixture(scope="module")
def dataset():
    rng = np.random.default_rng(11)
    return rng.standard_normal((4000, 32)).astype(np.float32)


@pytest.fixture(scope="module")
def queries():
    rng = np.random.default_rng(12)
    return rng.standard_normal((40, 32)).astype(np.float32)


class TestInterleavedCodecs:
    def test_pq_bitfield_roundtrip(self):
        rng = np.random.default_rng(0)
        for pq_bits in (4, 5, 8):
            codes = rng.integers(0, 1 << pq_bits,
                                 size=(71, 24)).astype(np.uint8)
            packed = rf._pack_interleaved_pq(codes, pq_bits)
            # reference extents: (ceil(71/32), ceil(24/chunk), 32, 16)
            chunk = (16 * 8) // pq_bits
            assert packed.shape == (3, -(-24 // chunk), 32, 16)
            got = rf._unpack_interleaved_pq(packed, 71, 24, pq_bits)
            np.testing.assert_array_equal(got, codes)

    def test_pq_bitfield_matches_reference_semantics(self):
        """Little-endian bitfield within each 16-byte chunk
        (ivf_pq_codepacking.cuh bitfield_view_t): code j occupies bits
        [j*bits, (j+1)*bits) of the chunk's byte stream."""
        codes = np.array([[0x3, 0xA, 0x5, 0xF]], np.uint8)  # pq_bits=4
        packed = rf._pack_interleaved_pq(codes, 4)
        # first two codes share byte 0: 0x3 | (0xA << 4)
        assert packed[0, 0, 0, 0] == 0x3 | (0xA << 4)
        assert packed[0, 0, 0, 1] == 0x5 | (0xF << 4)

    def test_rows_roundtrip(self):
        rng = np.random.default_rng(1)
        rows = rng.standard_normal((37, 12)).astype(np.float32)
        packed = rf._pack_interleaved_rows(rows, veclen=4)
        assert packed.shape == (2, 3, 32, 4)
        got = rf._unpack_interleaved_rows(packed, 37)
        np.testing.assert_array_equal(got, rows)


class TestIvfPqFile:
    def test_roundtrip_search_identical(self, dataset, queries):
        index = ivf_pq.build(dataset, ivf_pq.IndexParams(
            n_lists=16, pq_dim=8, seed=0))
        buf = io.BytesIO()
        rf.save_raft_ivf_pq(index, buf)
        buf.seek(0)
        loaded = rf.load_raft_ivf_pq(buf)
        assert loaded.pq_bits == index.pq_bits
        assert loaded.n_lists == index.n_lists
        sp = ivf_pq.SearchParams(n_probes=8)
        _, i1 = ivf_pq.search(index, queries, 10, sp)
        _, i2 = ivf_pq.search(loaded, queries, 10, sp)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))

    def test_roundtrip_pq_bits_4(self, dataset, queries):
        index = ivf_pq.build(dataset, ivf_pq.IndexParams(
            n_lists=8, pq_dim=16, pq_bits=4, seed=0))
        buf = io.BytesIO()
        rf.save_raft_ivf_pq(index, buf)
        buf.seek(0)
        loaded = rf.load_raft_ivf_pq(buf)
        # the in-memory index may carry capacity slack; compare the
        # dense (valid-rows-only) form the file stores
        codes = np.asarray(index.codes)
        ids = np.asarray(index.source_ids)
        off, sizes = index.list_offsets, index.list_sizes
        dense_c = np.concatenate([codes[int(off[l]) : int(off[l]) + int(s)]
                                  for l, s in enumerate(sizes)])
        dense_i = np.concatenate([ids[int(off[l]) : int(off[l]) + int(s)]
                                  for l, s in enumerate(sizes)])
        np.testing.assert_array_equal(np.asarray(loaded.codes), dense_c)
        np.testing.assert_array_equal(np.asarray(loaded.source_ids),
                                      dense_i)

    def test_frame_layout_is_npy(self, dataset, tmp_path):
        """Every frame is a standalone .npy blob readable by numpy."""
        index = ivf_pq.build(dataset, ivf_pq.IndexParams(
            n_lists=4, pq_dim=8, seed=0))
        p = tmp_path / "idx.ivf_pq"
        rf.save_raft_ivf_pq(index, p)
        with open(p, "rb") as f:
            ver = np.lib.format.read_array(f)
            assert ver[()] == 3 and ver.dtype == np.int32
            size = np.lib.format.read_array(f)
            assert size[()] == 4000 and size.dtype == np.int64


class TestIvfFlatFile:
    def test_roundtrip_search_identical(self, dataset, queries):
        index = ivf_flat.build(dataset, ivf_flat.IndexParams(
            n_lists=16, seed=0))
        buf = io.BytesIO()
        rf.save_raft_ivf_flat(index, buf)
        buf.seek(0)
        loaded = rf.load_raft_ivf_flat(buf)
        sp = ivf_flat.SearchParams(n_probes=16)
        _, i1 = ivf_flat.search(index, queries, 10, sp)
        _, i2 = ivf_flat.search(loaded, queries, 10, sp)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
        # exhaustive probes must equal the exact answer too
        _, want = naive_knn(dataset, queries, 10)
        assert calc_recall(np.asarray(i2), want) == 1.0

    def test_bf16_storage_rejected(self, dataset):
        index = ivf_flat.build(dataset, ivf_flat.IndexParams(
            n_lists=8, seed=0, dtype="bfloat16"))
        from raft_tpu.core import RaftError
        with pytest.raises(RaftError):
            rf.save_raft_ivf_flat(index, io.BytesIO())


class TestCagraFile:
    def test_roundtrip_search_identical(self, dataset, queries):
        index = cagra.build(dataset, cagra.IndexParams(
            graph_degree=16, intermediate_graph_degree=24, seed=0))
        buf = io.BytesIO()
        rf.save_raft_cagra(index, buf)
        buf.seek(0)
        loaded = rf.load_raft_cagra(buf)
        sp = cagra.SearchParams(itopk_size=32)
        _, i1 = cagra.search(index, queries, 10, sp)
        _, i2 = cagra.search(loaded, queries, 10, sp)
        # seeds are not part of the reference format; compare recall, not
        # identity (the traversal differs without the shared seed set)
        _, want = naive_knn(dataset, queries, 10)
        r1 = calc_recall(np.asarray(i1), want)
        r2 = calc_recall(np.asarray(i2), want)
        assert r2 >= r1 - 0.05, (r1, r2)

    def test_without_dataset(self, dataset):
        index = cagra.build(dataset, cagra.IndexParams(
            graph_degree=8, intermediate_graph_degree=12, seed=0))
        buf = io.BytesIO()
        rf.save_raft_cagra(index, buf, include_dataset=False)
        buf.seek(0)
        loaded = rf.load_raft_cagra(buf, dataset=dataset)
        np.testing.assert_array_equal(np.asarray(loaded.graph),
                                      np.asarray(index.graph))


# --------------------------------------------------------------------------
# byte-level goldens against the C++ wire format
# --------------------------------------------------------------------------

def cxx_frame(descr: str, shape: tuple, payload: bytes) -> bytes:
    """One npy frame EXACTLY as the reference's write_header emits it
    (mdspan_numpy_serializer.hpp:316-341): no trailing comma in the
    dict, 64-byte-aligned space padding, trailing newline."""
    if len(shape) == 0:
        shp = "()"
    elif len(shape) == 1:
        shp = "(%d,)" % shape[0]
    else:
        shp = "(" + ", ".join(str(s) for s in shape) + ")"
    d = "{'descr': '%s', 'fortran_order': False, 'shape': %s}" % (descr, shp)
    preamble = 6 + 2 + 2 + len(d) + 1
    pad = 64 - preamble % 64
    body = d + " " * pad + "\n"
    return (b"\x93NUMPY" + bytes([1, 0])
            + struct.pack("<H", len(body)) + body.encode("ascii") + payload)


def cxx_scalar(value, np_dtype) -> bytes:
    a = np.asarray(value, np_dtype)
    return cxx_frame(a.dtype.str if a.dtype.itemsize > 1
                     else "|" + a.dtype.str[1:], (), a.tobytes())


def cxx_mdspan(arr: np.ndarray) -> bytes:
    dt = arr.dtype
    descr = dt.str if dt.itemsize > 1 else "|" + dt.str[1:]
    return cxx_frame(descr, arr.shape, np.ascontiguousarray(arr).tobytes())


def interleave_flat_cxx(rows: np.ndarray, veclen: int) -> np.ndarray:
    """Plain-loop independent encoder of the in-memory interleaved group
    layout (ivf_flat_types.hpp:114-166): row r, component j lives at
    [r//32][j//veclen][r%32][j%veclen]. Input is already padded to a
    multiple of 32 rows; returns the flat (rounded, dim) frame view."""
    rounded, dim = rows.shape
    out = np.zeros((rounded // 32, dim // veclen, 32, veclen), rows.dtype)
    for r in range(rounded):
        for j in range(dim):
            out[r // 32, j // veclen, r % 32, j % veclen] = rows[r, j]
    return out.reshape(rounded, dim)


def walk_frames(raw: bytes, offset: int = 0):
    """Parse a byte stream into [(descr, shape, payload bytes)] without
    numpy's reader, so header-format differences can't mask a bug."""
    frames = []
    i = offset
    while i < len(raw):
        assert raw[i : i + 6] == b"\x93NUMPY", f"bad magic at {i}"
        assert raw[i + 6 : i + 8] == bytes([1, 0])
        (hlen,) = struct.unpack("<H", raw[i + 8 : i + 10])
        header = ast.literal_eval(raw[i + 10 : i + 10 + hlen]
                                  .decode("ascii").strip())
        shape = header["shape"]
        n = int(np.prod(shape)) if shape else 1
        itemsize = int(header["descr"][2:])
        start = i + 10 + hlen
        frames.append((header["descr"], shape,
                       raw[start : start + n * itemsize]))
        i = start + n * itemsize
    return frames


@pytest.fixture(scope="module")
def flat_golden():
    """A reference-style .ivf_flat byte stream built independently:
    dim=8 (f32 veclen=4), n_lists=3, sizes [5, 0, 37] — exercises the
    32-row rounding (5→32, 37→64), an empty list, and index padding."""
    rng = np.random.default_rng(7)
    dim, n_lists = 8, 3
    sizes = [5, 0, 37]
    rows = [rng.standard_normal((s, dim)).astype(np.float32)
            for s in sizes]
    ids = [np.arange(100 * i, 100 * i + s, dtype=np.int64)
           for i, s in enumerate(sizes)]
    centers = rng.standard_normal((n_lists, dim)).astype(np.float32)
    norms = (centers * centers).sum(1).astype(np.float32)

    blob = b"<f4\0"                                  # dtype tag
    blob += cxx_scalar(4, np.int32)                  # version
    blob += cxx_scalar(sum(sizes), np.int64)         # size (IdxT=int64)
    blob += cxx_scalar(dim, np.uint32)
    blob += cxx_scalar(n_lists, np.uint32)
    blob += cxx_scalar(0, np.int32)                  # metric L2Expanded: i4
    blob += cxx_scalar(0, np.uint8)                  # adaptive: bool -> u1
    blob += cxx_scalar(0, np.uint8)                  # conservative
    blob += cxx_mdspan(centers)
    blob += cxx_scalar(1, np.uint8)                  # has_norms
    blob += cxx_mdspan(norms)
    blob += cxx_mdspan(np.asarray(sizes, np.uint32))
    for li, s in enumerate(sizes):
        rounded = -(-s // 32) * 32
        blob += cxx_scalar(rounded, np.uint32)       # roundUp'd scalar
        if s == 0:
            continue
        padded = np.zeros((rounded, dim), np.float32)
        padded[:s] = rows[li]
        blob += cxx_mdspan(interleave_flat_cxx(padded, veclen=4))
        inds = np.full(rounded, -1, np.int64)        # kInvalidRecord
        inds[:s] = ids[li]
        blob += cxx_mdspan(inds)
    return blob, rows, ids, centers, sizes


class TestReferenceWireFormat:
    def test_flat_load_reference_bytes(self, flat_golden):
        blob, rows, ids, centers, sizes = flat_golden
        idx = rf.load_raft_ivf_flat(io.BytesIO(blob))
        assert idx.n_lists == 3 and idx.size == sum(sizes)
        np.testing.assert_array_equal(np.asarray(idx.centers), centers)
        got_rows = np.asarray(idx.data)
        got_ids = np.asarray(idx.source_ids)
        off = 0
        for li, s in enumerate(sizes):
            lo = int(idx.list_offsets[li])
            np.testing.assert_array_equal(got_rows[lo : lo + s], rows[li])
            np.testing.assert_array_equal(got_ids[lo : lo + s],
                                          ids[li].astype(np.int32))
            off += s

    def test_flat_save_matches_reference_bytes(self, flat_golden):
        """save() of the loaded golden reproduces the reference stream
        frame for frame: same 4-byte tag, same scalar DTYPES (i4 metric,
        u1 bools, u4 rounded list sizes), same interleaved payload bytes
        including the kInvalidRecord index padding."""
        blob, *_ = flat_golden
        idx = rf.load_raft_ivf_flat(io.BytesIO(blob))
        buf = io.BytesIO()
        rf.save_raft_ivf_flat(idx, buf)
        ours = buf.getvalue()
        assert ours[:4] == blob[:4] == b"<f4\0"
        want = walk_frames(blob, offset=4)
        got = walk_frames(ours, offset=4)
        assert len(got) == len(want)
        for k, ((d1, s1, p1), (d2, s2, p2)) in enumerate(zip(want, got)):
            assert d2 == d1, f"frame {k}: descr {d2} != {d1}"
            assert tuple(s2) == tuple(s1), f"frame {k}: shape {s2}!={s1}"
            assert p2 == p1, f"frame {k}: payload differs"

    def test_cagra_load_reference_bytes(self):
        rng = np.random.default_rng(8)
        n, dim, degree = 10, 4, 3
        ds = rng.standard_normal((n, dim)).astype(np.float32)
        graph = rng.integers(0, n, (n, degree)).astype(np.uint32)
        blob = b"<f4\0"
        blob += cxx_scalar(3, np.int32)          # serialization_version=3
        blob += cxx_scalar(n, np.uint32)         # size: IdxT=uint32
        blob += cxx_scalar(dim, np.uint32)
        blob += cxx_scalar(degree, np.uint32)
        blob += cxx_scalar(0, np.int32)          # metric
        blob += cxx_mdspan(graph)
        blob += cxx_scalar(1, np.uint8)          # include_dataset
        blob += cxx_mdspan(ds)
        idx = rf.load_raft_cagra(io.BytesIO(blob))
        np.testing.assert_array_equal(np.asarray(idx.graph), graph)
        np.testing.assert_array_equal(np.asarray(idx.dataset), ds)

        buf = io.BytesIO()
        rf.save_raft_cagra(idx, buf)
        ours = buf.getvalue()
        assert ours[:4] == b"<f4\0"
        want = walk_frames(blob, offset=4)
        got = walk_frames(ours, offset=4)
        assert len(got) == len(want)
        for k, ((d1, s1, p1), (d2, s2, p2)) in enumerate(zip(want, got)):
            assert d2 == d1, f"frame {k}: descr {d2} != {d1}"
            assert tuple(s2) == tuple(s1)
            assert p2 == p1, f"frame {k}: payload differs"

    def test_pq_scalar_widths(self, dataset):
        """IVF-PQ: NO dtype tag; enum/bool scalar frames carry the C++
        widths (i4 metric + codebook_kind, u1 conservative flag)."""
        index = ivf_pq.build(dataset, ivf_pq.IndexParams(
            n_lists=4, pq_dim=8, seed=0))
        buf = io.BytesIO()
        rf.save_raft_ivf_pq(index, buf)
        raw = buf.getvalue()
        assert raw[:6] == b"\x93NUMPY"            # no tag: frame 0 starts
        frames = walk_frames(raw)
        descrs = [f[0] for f in frames[:9]]
        assert descrs == ["<i4", "<i8", "<u4", "<u4", "<u4",
                          "|u1", "<i4", "<i4", "<u4"]
