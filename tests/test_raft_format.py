"""RAFT-native index file interop (core/raft_format.py): round-trips
through the reference's npy-frame serialization layout
(detail/ivf_pq_serialize.cuh, ivf_flat_serialize.cuh, cagra_serialize.cuh)
and unit checks of the interleaved bitfield codecs."""
import io

import jax.numpy as jnp
import numpy as np
import pytest

from ann_utils import calc_recall, naive_knn
from raft_tpu.core import raft_format as rf
from raft_tpu.neighbors import cagra, ivf_flat, ivf_pq


@pytest.fixture(scope="module")
def dataset():
    rng = np.random.default_rng(11)
    return rng.standard_normal((4000, 32)).astype(np.float32)


@pytest.fixture(scope="module")
def queries():
    rng = np.random.default_rng(12)
    return rng.standard_normal((40, 32)).astype(np.float32)


class TestInterleavedCodecs:
    def test_pq_bitfield_roundtrip(self):
        rng = np.random.default_rng(0)
        for pq_bits in (4, 5, 8):
            codes = rng.integers(0, 1 << pq_bits,
                                 size=(71, 24)).astype(np.uint8)
            packed = rf._pack_interleaved_pq(codes, pq_bits)
            # reference extents: (ceil(71/32), ceil(24/chunk), 32, 16)
            chunk = (16 * 8) // pq_bits
            assert packed.shape == (3, -(-24 // chunk), 32, 16)
            got = rf._unpack_interleaved_pq(packed, 71, 24, pq_bits)
            np.testing.assert_array_equal(got, codes)

    def test_pq_bitfield_matches_reference_semantics(self):
        """Little-endian bitfield within each 16-byte chunk
        (ivf_pq_codepacking.cuh bitfield_view_t): code j occupies bits
        [j*bits, (j+1)*bits) of the chunk's byte stream."""
        codes = np.array([[0x3, 0xA, 0x5, 0xF]], np.uint8)  # pq_bits=4
        packed = rf._pack_interleaved_pq(codes, 4)
        # first two codes share byte 0: 0x3 | (0xA << 4)
        assert packed[0, 0, 0, 0] == 0x3 | (0xA << 4)
        assert packed[0, 0, 0, 1] == 0x5 | (0xF << 4)

    def test_rows_roundtrip(self):
        rng = np.random.default_rng(1)
        rows = rng.standard_normal((37, 12)).astype(np.float32)
        packed = rf._pack_interleaved_rows(rows, veclen=4)
        assert packed.shape == (2, 3, 32, 4)
        got = rf._unpack_interleaved_rows(packed, 37)
        np.testing.assert_array_equal(got, rows)


class TestIvfPqFile:
    def test_roundtrip_search_identical(self, dataset, queries):
        index = ivf_pq.build(dataset, ivf_pq.IndexParams(
            n_lists=16, pq_dim=8, seed=0))
        buf = io.BytesIO()
        rf.save_raft_ivf_pq(index, buf)
        buf.seek(0)
        loaded = rf.load_raft_ivf_pq(buf)
        assert loaded.pq_bits == index.pq_bits
        assert loaded.n_lists == index.n_lists
        sp = ivf_pq.SearchParams(n_probes=8)
        _, i1 = ivf_pq.search(index, queries, 10, sp)
        _, i2 = ivf_pq.search(loaded, queries, 10, sp)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))

    def test_roundtrip_pq_bits_4(self, dataset, queries):
        index = ivf_pq.build(dataset, ivf_pq.IndexParams(
            n_lists=8, pq_dim=16, pq_bits=4, seed=0))
        buf = io.BytesIO()
        rf.save_raft_ivf_pq(index, buf)
        buf.seek(0)
        loaded = rf.load_raft_ivf_pq(buf)
        # the in-memory index may carry capacity slack; compare the
        # dense (valid-rows-only) form the file stores
        codes = np.asarray(index.codes)
        ids = np.asarray(index.source_ids)
        off, sizes = index.list_offsets, index.list_sizes
        dense_c = np.concatenate([codes[int(off[l]) : int(off[l]) + int(s)]
                                  for l, s in enumerate(sizes)])
        dense_i = np.concatenate([ids[int(off[l]) : int(off[l]) + int(s)]
                                  for l, s in enumerate(sizes)])
        np.testing.assert_array_equal(np.asarray(loaded.codes), dense_c)
        np.testing.assert_array_equal(np.asarray(loaded.source_ids),
                                      dense_i)

    def test_frame_layout_is_npy(self, dataset, tmp_path):
        """Every frame is a standalone .npy blob readable by numpy."""
        index = ivf_pq.build(dataset, ivf_pq.IndexParams(
            n_lists=4, pq_dim=8, seed=0))
        p = tmp_path / "idx.ivf_pq"
        rf.save_raft_ivf_pq(index, p)
        with open(p, "rb") as f:
            ver = np.lib.format.read_array(f)
            assert ver[()] == 3 and ver.dtype == np.int32
            size = np.lib.format.read_array(f)
            assert size[()] == 4000 and size.dtype == np.int64


class TestIvfFlatFile:
    def test_roundtrip_search_identical(self, dataset, queries):
        index = ivf_flat.build(dataset, ivf_flat.IndexParams(
            n_lists=16, seed=0))
        buf = io.BytesIO()
        rf.save_raft_ivf_flat(index, buf)
        buf.seek(0)
        loaded = rf.load_raft_ivf_flat(buf)
        sp = ivf_flat.SearchParams(n_probes=16)
        _, i1 = ivf_flat.search(index, queries, 10, sp)
        _, i2 = ivf_flat.search(loaded, queries, 10, sp)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
        # exhaustive probes must equal the exact answer too
        _, want = naive_knn(dataset, queries, 10)
        assert calc_recall(np.asarray(i2), want) == 1.0

    def test_bf16_storage_rejected(self, dataset):
        index = ivf_flat.build(dataset, ivf_flat.IndexParams(
            n_lists=8, seed=0, dtype="bfloat16"))
        from raft_tpu.core import RaftError
        with pytest.raises(RaftError):
            rf.save_raft_ivf_flat(index, io.BytesIO())


class TestCagraFile:
    def test_roundtrip_search_identical(self, dataset, queries):
        index = cagra.build(dataset, cagra.IndexParams(
            graph_degree=16, intermediate_graph_degree=24, seed=0))
        buf = io.BytesIO()
        rf.save_raft_cagra(index, buf)
        buf.seek(0)
        loaded = rf.load_raft_cagra(buf)
        sp = cagra.SearchParams(itopk_size=32)
        _, i1 = cagra.search(index, queries, 10, sp)
        _, i2 = cagra.search(loaded, queries, 10, sp)
        # seeds are not part of the reference format; compare recall, not
        # identity (the traversal differs without the shared seed set)
        _, want = naive_knn(dataset, queries, 10)
        r1 = calc_recall(np.asarray(i1), want)
        r2 = calc_recall(np.asarray(i2), want)
        assert r2 >= r1 - 0.05, (r1, r2)

    def test_without_dataset(self, dataset):
        index = cagra.build(dataset, cagra.IndexParams(
            graph_degree=8, intermediate_graph_degree=12, seed=0))
        buf = io.BytesIO()
        rf.save_raft_cagra(index, buf, include_dataset=False)
        buf.seek(0)
        loaded = rf.load_raft_cagra(buf, dataset=dataset)
        np.testing.assert_array_equal(np.asarray(loaded.graph),
                                      np.asarray(index.graph))
