"""CAGRA graph-build fast paths (build_knn_graph rework).

Covers the two TPU-native builders: the fused all-pairs route must be
BIT-IDENTICAL to the matmul reference engine (the fused kernel retires
ties in lax.top_k order, so the whole graph matches — order included),
and batched NN-descent (ops/nn_descent.py) must reach ≥0.9 graph-edge
recall deterministically, fall back to the exact path under the
``cagra.nn_descent`` guard, and be invariant to its batch partition
(round-delayed updates: every batch reads the previous round's state).

Budget note: the fused tests pin one corpus-wide tile (one interpret
grid step) and share one (1000, 24, k=19) geometry with the guarded /
fallback tests so interpret-mode executables are cache hits.
"""
import numpy as np
import pytest

from ann_utils import calc_recall, naive_knn
from raft_tpu.core import faults
from raft_tpu.neighbors import cagra
from raft_tpu.ops import nn_descent as nnd


def clustered(n, d, seed=0, intrinsic=8, clusters=50):
    """Low-intrinsic-dimension clustered mixture — the bench corpus
    shape. NN-descent's convergence (like IVF recall) is measured on
    the workload's structure, not on distance-concentrated uniform
    noise."""
    rng = np.random.default_rng(seed)
    w = rng.standard_normal((intrinsic, d)).astype(np.float32)
    w /= np.linalg.norm(w, axis=1, keepdims=True)
    cz = rng.standard_normal((clusters, intrinsic)).astype(np.float32)
    z = (cz[rng.integers(0, clusters, n)]
         + rng.standard_normal((n, intrinsic)).astype(np.float32))
    return (z @ w + 0.1 * rng.standard_normal((n, d)).astype(np.float32)
            ).astype(np.float32)


def exact_graph_oracle(x, k, chunk=2000):
    """Exact (n, k) self-excluded kNN graph via the NumPy oracle,
    query-chunked so the (chunk, n) distance block bounds host memory."""
    out = []
    for c0 in range(0, len(x), chunk):
        _, ids = naive_knn(x, x[c0:c0 + chunk], k + 1)
        rows = np.arange(c0, min(c0 + chunk, len(x)))[:, None]
        order = np.argsort(~(ids != rows), axis=1, kind="stable")[:, :k]
        out.append(np.take_along_axis(ids, order, axis=1))
    return np.concatenate(out)


@pytest.fixture(scope="module")
def small():
    rng = np.random.default_rng(11)
    return rng.standard_normal((1000, 24)).astype(np.float32)


@pytest.fixture(scope="module")
def small_matmul_graph(small):
    return cagra.build_knn_graph(small, 19, algo="brute", engine="matmul")


class TestFusedGraph:
    def test_fused_bit_identical_to_matmul(self, small, small_matmul_graph,
                                           monkeypatch):
        # one corpus-wide tile keeps the interpret grid at one step
        monkeypatch.setenv("RAFT_TPU_FUSED_TILES", "1024,1024")
        g_f = cagra.build_knn_graph(small, 19, algo="brute",
                                    engine="fused")
        np.testing.assert_array_equal(g_f, small_matmul_graph)

    def test_fused_guarded_falls_back_bit_identical(self, small,
                                                    small_matmul_graph,
                                                    monkeypatch):
        """Kernel failure mid-sweep: the brute_force.fused guard serves
        the GEMM engine — same graph — without demoting the site
        (injected faults simulate per-call failure)."""
        monkeypatch.setenv("RAFT_TPU_FUSED_TILES", "1024,1024")
        with faults.inject("kernel_compile", "brute_force.fused"):
            g_f = cagra.build_knn_graph(small, 19, algo="brute",
                                        engine="fused")
        np.testing.assert_array_equal(g_f, small_matmul_graph)
        from raft_tpu.ops.guarded import demoted_sites

        assert "brute_force.fused" not in demoted_sites()

    def test_fused_parted_bit_identical_to_matmul(self, small,
                                                  monkeypatch):
        """The parted sweep shares the engine choice: per-part fused
        searches (eager prepare_fused BEFORE the jit trace, valid_rows
        masking the tail pad — part 1 here is 488/512 valid) must merge
        to the same graph as the matmul parted path, bit for bit."""
        monkeypatch.setenv("RAFT_TPU_FUSED_TILES", "1024,1024")
        monkeypatch.setenv("RAFT_TPU_CAGRA_BRUTE_PART_N", "600")
        g_m = cagra.build_knn_graph(small, 19, algo="brute",
                                    engine="matmul")
        g_f = cagra.build_knn_graph(small, 19, algo="brute",
                                    engine="fused")
        np.testing.assert_array_equal(g_f, g_m)

    def test_progress_hook(self, small):
        calls = []
        cagra.build_knn_graph(
            small, 19, algo="brute", engine="matmul", batch=256,
            progress=lambda done, total, s: calls.append((done, total)))
        assert calls == [(256, 1000), (512, 1000), (768, 1000),
                         (1000, 1000)]


class TestNnDescentGraph:
    def test_recall_and_determinism(self):
        x = clustered(1000, 32, seed=5)
        k = 16
        g1 = nnd.build_graph(x, k, rounds=5, seed=3)
        want = exact_graph_oracle(x, k)
        r = calc_recall(g1, want)
        assert r >= 0.9, f"nn_descent graph recall {r}"
        assert (g1 != np.arange(len(x))[:, None]).all()   # no self edges
        assert g1.min() >= 0 and g1.max() < len(x)        # all slots valid
        # jax PRNG + stable sorts: bit-identical per seed across runs
        g2 = nnd.build_graph(x, k, rounds=5, seed=3)
        np.testing.assert_array_equal(g1, g2)

    @pytest.mark.slow
    def test_batch_invariance(self):
        """Round-delayed updates make the result independent of the
        batch partition (batch=1024 on 1600 rows exercises the
        wrapped-tail multi-batch path AND its update-rate row masking).
        Slow lane: the second batch shape recompiles the whole round
        program — ~3s of pure compile the tier-1 wall can't spare."""
        x = clustered(1600, 32, seed=5)
        g1 = nnd.build_graph(x, 16, rounds=8, seed=3)
        g2 = nnd.build_graph(x, 16, rounds=8, seed=3, batch=1024)
        np.testing.assert_array_equal(g1, g2)

    def test_init_graph_warm_start(self):
        """Seeding from candidate lists (the IVF-PQ pass contract): an
        exact init must survive a descent round ~intact (entries are
        only displaced by strictly better candidates, modulo ties).
        Same (n, d, k, batch) geometry as the determinism test — the
        round executables are cache hits."""
        x = clustered(1000, 32, seed=9)
        want = exact_graph_oracle(x, 16)
        g = cagra.build_knn_graph(x, 16, algo="nn_descent", nnd_rounds=1,
                                  init_graph=want)
        assert calc_recall(g, want) >= 0.99

    def test_guarded_fallback_parity(self, small, small_matmul_graph):
        """Builder failure → the exact path (bit-identical to a direct
        brute build at this size), no demotion from an injected fault."""
        with faults.inject("kernel_compile", "cagra.nn_descent"):
            got = cagra.build_knn_graph(small, 19, algo="nn_descent")
        np.testing.assert_array_equal(got, small_matmul_graph)
        from raft_tpu.ops.guarded import demoted_sites

        assert "cagra.nn_descent" not in demoted_sites()

    @pytest.mark.slow
    def test_recall_at_20k(self):
        """The issue's quality bar at the builder's real operating
        regime: ≥0.9 graph-edge recall at 20k rows on the bench corpus
        shape (determinism is asserted at 1k above — the mechanism is
        scale-invariant)."""
        x = clustered(20_000, 64, seed=7, intrinsic=16, clusters=200)
        k = 32
        g = cagra.build_knn_graph(x, k, algo="nn_descent")
        r = calc_recall(g, exact_graph_oracle(x, k))
        assert r >= 0.9, f"nn_descent 20k graph recall {r}"


class TestAutoPolicy:
    def test_threshold_and_race_verdict(self, monkeypatch):
        from raft_tpu.distance.distance_types import DistanceType

        l2 = DistanceType.L2Expanded
        monkeypatch.setenv("RAFT_TPU_CAGRA_BRUTE_N", "500")
        assert cagra._resolve_graph_algo(400, 32, 16, "auto", l2) == "brute"
        assert cagra._resolve_graph_algo(600, 32, 16, "auto", l2) == \
            "nn_descent"
        assert cagra._resolve_graph_algo(600, 32, 16, "ivf_pq", l2) == \
            "ivf_pq"
        # a recorded race verdict (the bench graph lane writes these)
        # overrides the threshold for its shape bucket — but only for
        # its OWN metric tag
        from raft_tpu.ops import autotune

        key = cagra._graph_algo_key(600, 32, 16, l2)
        autotune.record(key, "ivf_pq", persist=False)
        try:
            assert cagra._resolve_graph_algo(600, 32, 16, "auto", l2) == \
                "ivf_pq"
            ip = DistanceType.InnerProduct
            assert cagra._resolve_graph_algo(600, 32, 16, "auto", ip) == \
                "nn_descent"
        finally:
            autotune.forget(key)

    def test_unsupported_metric_routes_around_nn_descent(self, small,
                                                         monkeypatch):
        """A descent-incapable metric must never reach the guarded
        builder: auto resolves to ivf_pq above the brute threshold, and
        an explicit ask raises BEFORE guarded_call — neither may persist
        a cagra.nn_descent demotion."""
        from raft_tpu.core.errors import RaftError
        from raft_tpu.distance.distance_types import DistanceType
        from raft_tpu.ops import nn_descent as nnd_mod
        from raft_tpu.ops.guarded import demoted_sites

        cos = DistanceType.CosineExpanded
        assert not nnd_mod.supports(cos)
        monkeypatch.setenv("RAFT_TPU_CAGRA_BRUTE_N", "500")
        assert cagra._resolve_graph_algo(600, 32, 16, "auto", cos) == \
            "ivf_pq"
        with pytest.raises(RaftError, match="nn_descent supports"):
            cagra.build_knn_graph(small, 19, metric=cos,
                                  algo="nn_descent")
        assert "cagra.nn_descent" not in demoted_sites()

    def test_build_stats_attached(self):
        x = clustered(500, 16, seed=2)
        idx = cagra.build(x, cagra.IndexParams(
            intermediate_graph_degree=16, graph_degree=8, seed=0))
        st = idx.build_stats
        assert st["knn_algo"] == "brute" and st["n"] == 500
        assert all(st[key] >= 0.0 for key in
                   ("knn_graph_s", "optimize_s", "seeds_s"))
