"""Fleet-layer tests (docs/mnmg.md): the topology planner, the
hierarchical ICI/DCN merge's bit-identity contract, the distributed
IVF-PQ build arc on a virtual multi-host mesh, and host-loss
degradation. Everything runs on the 8-device virtual CPU mesh; the
2-process loopback-DCN acceptance harness
(``scratch/run_fleet_dryrun.py``) is wrapped as a slow+distributed
test."""
import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from raft_tpu.core.errors import RaftError
from raft_tpu.neighbors import ivf_pq
from raft_tpu.ops import ring_topk
from raft_tpu.parallel import Fleet, Topology, sharded_ann
from raft_tpu.parallel import fleet as fleet_mod
from raft_tpu.parallel import topology as topo_mod
from raft_tpu.utils import shard_map_compat

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestTopology:
    def test_groups_and_numbering(self):
        t = Topology(2, 4)
        assert (t.n_shards, t.multi_host) == (8, True)
        assert t.host_of(0) == 0 and t.host_of(5) == 1
        assert list(t.shards_of(1)) == [4, 5, 6, 7]
        assert t.host_groups() == ((0, 1, 2, 3), (4, 5, 6, 7))
        assert t.cross_groups() == ((0, 4), (1, 5), (2, 6), (3, 7))
        t42 = Topology(4, 2)
        assert t42.host_groups() == ((0, 1), (2, 3), (4, 5), (6, 7))
        assert t42.cross_groups() == ((0, 2, 4, 6), (1, 3, 5, 7))

    def test_single_host_topology(self):
        t = Topology(1, 8)
        assert not t.multi_host
        assert t.host_groups() == ((0, 1, 2, 3, 4, 5, 6, 7),)

    def test_detect_single_process(self):
        assert topo_mod.detect() == Topology(1, jax.device_count())

    def test_invalid(self):
        with pytest.raises(RaftError):
            Topology(0, 2)
        with pytest.raises(RaftError):
            Topology(2, 2).host_of(4)

    def test_fleet_mesh_virtual(self):
        for h, d in ((2, 4), (4, 2), (2, 2)):
            mesh, topo = topo_mod.fleet_mesh(topo_mod.virtual(h, d))
            assert mesh.shape[topo_mod.AXIS] == h * d
            assert topo == Topology(h, d)

    def test_plan_merge_dcn_reduction(self):
        plan = topo_mod.plan_merge(Topology(2, 4), m=128, k=10)
        assert plan["engine"] == "hier"
        assert plan["dcn_reduction"] == 4
        assert (plan["flat_dcn_bytes_per_device"]
                == 4 * plan["dcn_bytes_per_device"])
        stages = [s["stage"] for s in plan["stages"]]
        assert stages == ["ici_ring", "dcn_allgather_fold"]
        flat = topo_mod.plan_merge(Topology(1, 8), m=128, k=10)
        assert flat["engine"] == "flat"
        assert flat["dcn_bytes_per_device"] == 0


class TestResolveEngine:
    def test_single_host_byte_identical(self):
        """A single-host topology (or none) must leave today's engine
        resolution untouched."""
        for m, k in ((64, 10), (512, 32), (8, 4)):
            base = ring_topk.resolve_engine(m, k, 8)
            assert ring_topk.resolve_engine(
                m, k, 8, topology=Topology(1, 8)) == base

    def test_multi_host_default_hier(self):
        assert ring_topk.resolve_engine(
            128, 10, 8, topology=Topology(2, 4)) == "hier"

    def test_multi_host_overrides(self):
        t = Topology(2, 4)
        assert ring_topk.resolve_engine(
            128, 10, 8, override="ring", topology=t) == "ring"
        assert ring_topk.resolve_engine(
            128, 10, 8, override="allgather", topology=t) == "allgather"
        # remote-DMA ring hops must not cross DCN
        assert ring_topk.resolve_engine(
            128, 10, 8, override="ring_pallas", topology=t) == "hier"
        assert ring_topk.resolve_engine(
            128, 10, 8, override="auto", topology=t) == "hier"

    def test_subgroup_comms_force_allgather(self):
        assert ring_topk.resolve_engine(
            128, 10, 8, plain_axis=False, topology=Topology(2, 4)) \
            == "allgather"

    def test_hier_merge_requires_topology(self):
        with pytest.raises(RaftError):
            ring_topk.merge(jnp.zeros((2, 3)),
                            jnp.zeros((2, 3), jnp.int32), 3, True,
                            axis_size=8, engine="hier")


def _merge_on(mesh, d, g, k, engine, topo=None):
    """Dispatch one merge over the stacked (p, m, w) candidates."""
    p = mesh.shape["shard"]

    def body(dd, gg):
        return ring_topk.merge(dd[0], gg[0], k, True, axis="shard",
                               axis_size=p, engine=engine, topology=topo)

    out = shard_map_compat(
        body, mesh=mesh,
        in_specs=(P("shard", None, None), P("shard", None, None)),
        out_specs=(P(), P()), check=False)(jnp.asarray(d), jnp.asarray(g))
    return np.asarray(out[0]), np.asarray(out[1])


@pytest.mark.multichip
class TestHierMergeBitIdentity:
    @pytest.mark.parametrize("hosts,devs", [(2, 4), (4, 2)])
    def test_hier_equals_flat_with_ties_and_sentinels(
            self, multichip_mesh, hosts, devs, rng):
        """The acceptance pin: the two-stage ICI/DCN merge must be
        BIT-identical to the flat allgather under the (±distance,
        concat-position) total order — including cross-host ties and a
        dead shard's (+inf, −1) sentinel rows."""
        p, m, k = 8, 16, 6
        topo = Topology(hosts, devs)
        d = rng.standard_normal((p, m, k)).astype(np.float32)
        g = rng.permutation(p * m * k).astype(np.int32).reshape(p, m, k)
        d[:, :, 0] = 0.5          # an 8-way cross-host tie on every query
        d[p - 1] = np.inf         # a dead shard: all-sentinel candidates
        g[p - 1] = -1
        fd, fi = _merge_on(multichip_mesh, d, g, k, "allgather")
        hd, hi = _merge_on(multichip_mesh, d, g, k, "hier", topo)
        np.testing.assert_array_equal(hi, fi)
        np.testing.assert_array_equal(hd, fd)

    def test_single_host_column_topology(self, multichip_mesh, rng):
        """H=8, D=1: stage 1 degenerates to a pass-through and the DCN
        fold alone must still match flat."""
        p, m, k = 8, 8, 4
        d = rng.standard_normal((p, m, k)).astype(np.float32)
        g = rng.permutation(p * m * k).astype(np.int32).reshape(p, m, k)
        fd, fi = _merge_on(multichip_mesh, d, g, k, "allgather")
        hd, hi = _merge_on(multichip_mesh, d, g, k, "hier", Topology(8, 1))
        np.testing.assert_array_equal(hi, fi)
        np.testing.assert_array_equal(hd, fd)


def _gt(base, q, k, rows=None):
    rows = np.arange(len(base)) if rows is None else np.asarray(rows)
    sub = base[rows]
    d2 = ((q[:, None, :] - sub[None, :, :]) ** 2).sum(-1)
    return rows[np.argsort(d2, axis=1, kind="stable")[:, :k]]


def _recall(found, want):
    hits = sum(len(set(found[i].tolist()) & set(want[i].tolist()))
               for i in range(len(want)))
    return hits / want.size


def test_effective_nprobe_widen():
    f = fleet_mod._effective_nprobe
    assert f(4, 1.0, 8) == 4          # healthy: untouched
    assert f(4, 0.5, 8) == 8          # half dark: double the probes
    assert f(4, 0.25, 8) == 8         # capped at n_lists
    assert f(1, 0.9, 100) == 2
    assert f(4, 0.0, 8) == 8          # degenerate frac clamps


@pytest.mark.multichip
class TestFleetArc:
    def test_host_loss_bookkeeping_no_build(self):
        """Host-granular loss bookkeeping without an index build (the
        tier-1-lean slice of the arc: transitions, events, debugz; the
        compile-heavy build+search arc runs in the slow lane)."""
        from raft_tpu.core import events
        from raft_tpu.serve import debugz

        fleet = Fleet.virtual(2, 2)
        assert fleet.merge_plan()["dcn_reduction"] == 2
        fleet.mark_host_failed(1)
        assert fleet.host_health()["hosts_down"] == [1]
        kinds = [e["kind"] for e in events.recent()]
        assert "host_lost" in kinds
        # transition-only: re-marking an already-down host is silent
        n_lost = kinds.count("host_lost")
        fleet.mark_host_failed(1)
        assert [e["kind"] for e in events.recent()].count(
            "host_lost") == n_lost
        fleet.mark_host_failed(1, ok=True)
        assert fleet.host_health()["hosts_down"] == []
        assert "host_restored" in [e["kind"] for e in events.recent()]

        snap = debugz.snapshot()
        ent = next(e for e in snap["fleet"] if e["topology"] == "2x2")
        assert ent["merge"] == {"engine": "hier", "dcn_reduction": 2}
        json.dumps(snap, allow_nan=False)
        assert "fleet" in debugz.render_text()

    @pytest.mark.slow
    def test_build_search_host_loss_probe(self, multichip_mesh, rng):
        """The full virtual-fleet arc: distributed build on a 2x2 fleet,
        hier search bit-identical to the forced flat merge, host loss →
        host-granular shards_ok + auto-widened recall over the
        survivors, canary re-admission, and the debugz fleet section."""
        from raft_tpu.core import events

        fleet = Fleet.virtual(2, 2)
        assert fleet.merge_plan()["dcn_reduction"] == 2
        base = rng.standard_normal((1024, 16)).astype(np.float32)
        q = rng.standard_normal((32, 16)).astype(np.float32)
        params = ivf_pq.IndexParams(n_lists=8, pq_dim=8, pq_bits=4,
                                    kmeans_n_iters=4, seed=3)
        sp = ivf_pq.SearchParams(n_probes=4)
        idx = fleet.build_ivf_pq(base, params)
        assert idx.topology is fleet.topology
        assert "fleet_build" in [e["kind"] for e in events.recent()]

        d, i, ok = fleet.search(idx, q, 10, sp)
        assert list(ok) == [True] * 4
        d2, i2, _ = fleet.search(idx, q, 10, sp, merge_engine="allgather")
        np.testing.assert_array_equal(np.asarray(i), np.asarray(i2))
        np.testing.assert_array_equal(np.asarray(d), np.asarray(d2))
        healthy = _recall(np.asarray(i), _gt(base, q, 10))
        assert healthy > 0.3, healthy

        fleet.mark_host_failed(1)
        hh = fleet.host_health()
        assert hh["hosts_ok"] == [True, False]
        assert hh["hosts_down"] == [1]
        assert abs(hh["served_frac"] - 0.5) < 0.05, hh
        dd, ii, ok3 = fleet.search(idx, q, 10, sp)
        assert list(ok3) == [True, True, False, False]
        surv = np.concatenate(sharded_ann._split_rows(1024, 4)[:2])
        ss = set(surv.tolist())
        assert all(x == -1 or x in ss
                   for x in np.asarray(ii).ravel().tolist()), \
            "dead host's rows leaked into degraded results"
        degraded = _recall(np.asarray(ii), _gt(base, q, 10, rows=surv))
        assert degraded >= 0.9 * healthy, (degraded, healthy)
        assert "host_lost" in [e["kind"] for e in events.recent()]

        rep = fleet.probe_hosts()
        assert rep["hosts_restored"] == [1], rep
        assert fleet.host_health()["served_frac"] == 1.0
        assert "host_restored" in [e["kind"] for e in events.recent()]
        d3, i3, ok4 = fleet.search(idx, q, 10, sp)
        assert list(ok4) == [True] * 4
        np.testing.assert_array_equal(np.asarray(i3), np.asarray(i))
        np.testing.assert_array_equal(np.asarray(d3), np.asarray(d))

        # ops surface: fleet section present, strict-JSON, rendered
        from raft_tpu.serve import debugz

        snap = debugz.snapshot()
        assert "fleet" in snap
        ent = next(e for e in snap["fleet"]
                   if e["topology"] == "2x2" and e["n_indexes"] >= 1)
        assert ent["merge"] == {"engine": "hier", "dcn_reduction": 2}
        assert ent["last_probe"]["hosts_restored"] == [1]
        json.dumps(snap, allow_nan=False)
        assert "fleet" in debugz.render_text()

    def test_single_host_fleet_keeps_flat_engines(self, multichip_mesh):
        fleet = Fleet.local(4)
        assert fleet.topology == Topology(1, 4)
        plan = fleet.merge_plan()
        assert plan["engine"] == "flat" and plan["dcn_bytes_per_device"] == 0

    def test_adopt_rejects_foreign_mesh(self, multichip_mesh, rng):
        fleet = Fleet.virtual(2, 2)

        class Foreign:
            mesh = multichip_mesh

        with pytest.raises(RaftError):
            fleet.adopt(Foreign())

    def test_build_rejects_per_cluster(self, multichip_mesh, rng):
        fleet = Fleet.virtual(2, 2)
        with pytest.raises(RaftError):
            fleet.build_ivf_pq(
                rng.standard_normal((256, 16)).astype(np.float32),
                ivf_pq.IndexParams(
                    n_lists=4, codebook_kind=ivf_pq.CodebookGen.PER_CLUSTER))


@pytest.mark.slow
@pytest.mark.distributed
def test_fleet_dryrun_two_process():
    """The MNMG acceptance harness: 2 loopback-DCN processes build the
    index, pin bit-identity against a single-process reference, and
    drill the host-loss arc (scratch/run_fleet_dryrun.py)."""
    script = os.path.join(_ROOT, "scratch", "run_fleet_dryrun.py")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)    # children set their own device counts
    r = subprocess.run([sys.executable, script], env=env,
                       capture_output=True, text=True, timeout=800)
    out = (r.stdout or "") + (r.stderr or "")
    if "SKIPPED" in out:
        pytest.skip(out[-500:])
    assert r.returncode == 0 and "FLEET_DRYRUN_OK" in out, out[-3000:]
