"""Self-healing serving tests (ISSUE 10): circuit breakers on guarded
kernels, shard re-probe/recovery, the SLO-driven brownout controller,
and timed fault scenarios.

Tier-1 coverage is lean by design (the 870 s wall has no margin): every
recovery drill runs on injectable clocks and numpy stubs — the only
device work is the probe_shards canary (a few 8-row slices). The full
chaos drill (overload + shard death + kernel fault → complete recovery
arc, ISSUE 10 acceptance) builds a real index and serves real traffic,
so it rides the ``slow``/``faults`` lane.
"""
import json
import time

import numpy as np
import pytest

from raft_tpu.core import events, faults
from raft_tpu.ops import autotune, guarded
from raft_tpu.serve import debugz, degrade, metrics, quality, slo
from raft_tpu.serve.degrade import BrownoutController

pytestmark = pytest.mark.faults


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    # guard demotions ride the autotune cache; tests must not touch the
    # user-level JSON
    monkeypatch.setenv("RAFT_TPU_AUTOTUNE_CACHE", "")
    events.clear()
    yield
    guarded.reset()


@pytest.fixture
def clock(monkeypatch):
    """Injectable breaker clock: advance with clock['t'] += s."""
    now = {"t": 0.0}
    monkeypatch.setattr(guarded, "_clock", lambda: now["t"])
    return now


def _boom():
    raise RuntimeError("kernel died")


class TestCircuitBreaker:
    @pytest.fixture(autouse=True)
    def _no_ambient_kernel_faults(self):
        # the faults lane (RAFT_TPU_FAULTS='kernel_compile@*') serves
        # every guarded call as an injected per-call failure — the
        # breaker arcs drilled here are unreachable by design
        if any(f.kind in ("kernel_compile", "kernel_fault")
               for f in faults.active()):
            pytest.skip("ambient kernel faults pre-empt the kernel path")

    def test_open_probe_backoff_reclose(self, clock):
        """The full arc on one site: real failure -> open; probation ->
        half-open probe; failed probe doubles the backoff (capped);
        successful probe re-closes and restores the kernel path."""
        calls = []

        def kern():
            calls.append(1)
            return "kern"

        assert guarded.guarded_call("sh.a", _boom, lambda: "fb") == "fb"
        b = guarded.breaker_snapshot()["sh.a"]
        assert b["state"] == "open" and b["backoff_s"] == 30.0
        assert autotune.lookup(guarded._guard_key("sh.a")) == "fallback"
        # inside probation: fallback, kernel untouched
        assert guarded.guarded_call("sh.a", kern, lambda: "fb") == "fb"
        assert not calls
        # probation over: ONE probe; it fails -> backoff doubles
        clock["t"] = 31.0
        assert guarded.guarded_call("sh.a", _boom, lambda: "fb") == "fb"
        b = guarded.breaker_snapshot()["sh.a"]
        assert b["backoff_s"] == 60.0 and b["probes"] == 1
        # healthy probe closes; verdict forgotten; kernel path restored
        clock["t"] = 95.0
        assert guarded.guarded_call("sh.a", kern, lambda: "fb") == "kern"
        assert "sh.a" not in guarded.demoted_sites()
        assert autotune.lookup(guarded._guard_key("sh.a")) is None
        assert guarded.guarded_call("sh.a", kern, lambda: "fb") == "kern"
        assert len(calls) == 2      # the probe + the restored call
        kinds = [e["kind"] for e in events.recent() if e["site"] == "sh.a"]
        assert kinds == ["breaker_open", "guarded_demotion",
                         "breaker_probe", "breaker_open",
                         "breaker_probe", "breaker_close"]
        # per-site gauge followed the transitions back to closed
        assert metrics.gauge("guarded.breaker.sh.a").value == 0

    def test_backoff_caps_and_env_knobs(self, clock, monkeypatch):
        monkeypatch.setenv("RAFT_TPU_GUARD_PROBE_AFTER_S", "2")
        monkeypatch.setenv("RAFT_TPU_GUARD_MAX_BACKOFF_S", "5")
        assert guarded.guarded_call("sh.cap", _boom, lambda: "fb") == "fb"
        for expect in (4.0, 5.0, 5.0):   # 2 -> 4 -> capped at 5
            clock["t"] += 6.0
            assert guarded.guarded_call(
                "sh.cap", _boom, lambda: "fb") == "fb"
            assert guarded.breaker_snapshot()["sh.cap"]["backoff_s"] \
                == expect

    def test_sticky_mode_probe_after_zero(self, clock, monkeypatch):
        """PROBE_AFTER_S <= 0 restores the pre-ISSUE-10 sticky demotion
        (an operator can pin a site down while debugging)."""
        monkeypatch.setenv("RAFT_TPU_GUARD_PROBE_AFTER_S", "0")
        assert guarded.guarded_call("sh.st", _boom, lambda: "fb") == "fb"
        clock["t"] = 1e9
        assert guarded.guarded_call(
            "sh.st", lambda: "kern", lambda: "fb") == "fb"
        assert guarded.breaker_snapshot()["sh.st"]["probes"] == 0

    def test_kernel_compile_injection_stays_per_call(self):
        """PR 1 invariant byte-for-byte: a kernel_compile injection is a
        per-call simulation — the breaker does not move."""
        with faults.inject("kernel_compile", "sh.i", count=1):
            assert guarded.guarded_call(
                "sh.i", lambda: "kern", lambda: "fb") == "fb"
        assert guarded.guarded_call(
            "sh.i", lambda: "kern", lambda: "fb") == "kern"
        assert "sh.i" not in guarded.breaker_snapshot()

    def test_kernel_fault_opens_recovers_never_persists(
            self, clock, monkeypatch, tmp_path):
        """kernel_fault drives the breaker (the drillable persistent
        failure) but can never poison another process: even under
        GUARD_PERSIST=1 an injected open stays out of the disk cache,
        and the probe re-closes the breaker once the fault clears."""
        cache = tmp_path / "autotune.json"
        monkeypatch.setenv("RAFT_TPU_AUTOTUNE_CACHE", str(cache))
        monkeypatch.setenv("RAFT_TPU_GUARD_PERSIST", "1")
        with faults.inject("kernel_fault", "sh.kf"):
            assert guarded.guarded_call(
                "sh.kf", lambda: "kern", lambda: "fb") == "fb"
            b = guarded.breaker_snapshot()["sh.kf"]
            assert b["state"] == "open" and b["injected"]
            # probe under the armed fault re-opens
            clock["t"] += 31.0
            assert guarded.guarded_call(
                "sh.kf", lambda: "kern", lambda: "fb") == "fb"
        # in-process verdict exists but never reached the disk cache
        assert autotune.lookup(guarded._guard_key("sh.kf")) == "fallback"
        autotune.record("unrelated_key", "x")      # triggers a disk dump
        disk = json.loads(cache.read_text())
        assert guarded._guard_key("sh.kf") not in disk
        autotune.forget("unrelated_key")
        # fault cleared: the probe restores steady-state dispatch
        clock["t"] += 120.0
        assert guarded.guarded_call(
            "sh.kf", lambda: "kern", lambda: "fb") == "kern"
        assert "sh.kf" not in guarded.demoted_sites()

    def test_injected_probe_failure_keeps_real_demotion_label(
            self, clock, monkeypatch, tmp_path):
        """A probe of a REAL-failure-opened breaker failing on an armed
        simulation must neither relabel the outage as injected nor drop
        the persisted verdict from the disk cache."""
        cache = tmp_path / "autotune.json"
        monkeypatch.setenv("RAFT_TPU_AUTOTUNE_CACHE", str(cache))
        monkeypatch.setenv("RAFT_TPU_GUARD_PERSIST", "1")
        assert guarded.guarded_call("sh.rl", _boom, lambda: "fb") == "fb"
        key = guarded._guard_key("sh.rl")
        assert key in json.loads(cache.read_text())
        clock["t"] += 31.0
        with faults.inject("kernel_compile", "sh.rl"):
            assert guarded.guarded_call(
                "sh.rl", lambda: "kern", lambda: "fb") == "fb"
        b = guarded.breaker_snapshot()["sh.rl"]
        assert b["state"] == "open" and b["injected"] is False
        autotune.record("unrelated_key2", "x")     # re-dumps the cache
        assert key in json.loads(cache.read_text()), \
            "injected probe failure dropped the persisted real demotion"
        autotune.forget("unrelated_key2")

    def test_persisted_demotion_seeds_open_and_recovers(
            self, clock, monkeypatch, tmp_path):
        """A prior process's persisted guard verdict seeds this
        process's breaker OPEN — it too probes and recovers instead of
        being demoted forever."""
        monkeypatch.setenv("RAFT_TPU_AUTOTUNE_CACHE",
                           str(tmp_path / "c.json"))
        autotune.record(guarded._guard_key("sh.pers"), "fallback")
        assert guarded.guarded_call(
            "sh.pers", lambda: "kern", lambda: "fb") == "fb"
        assert guarded.breaker_snapshot()["sh.pers"]["state"] == "open"
        clock["t"] = 31.0
        assert guarded.guarded_call(
            "sh.pers", lambda: "kern", lambda: "fb") == "kern"
        assert autotune.lookup(guarded._guard_key("sh.pers")) is None

    def test_probe_never_strands_on_base_exception(self, clock):
        """A probe exiting with a BaseException outside the handled set
        (e.g. a cancelled-future error) must re-arm the breaker open —
        a stranded probing flag would disable every future probe."""
        class Boom(BaseException):
            pass

        def base_boom():
            raise Boom()

        assert guarded.guarded_call("sh.be", _boom, lambda: "fb") == "fb"
        clock["t"] += 31.0
        with pytest.raises(Boom):
            guarded.guarded_call("sh.be", base_boom, lambda: "fb")
        b = guarded.breaker_snapshot()["sh.be"]
        assert b["state"] == "open"
        # the next call can probe again immediately (abort, not failure:
        # no backoff doubling, no stranded half-open)
        assert guarded.guarded_call(
            "sh.be", lambda: "kern", lambda: "fb") == "kern"
        assert "sh.be" not in guarded.demoted_sites()

    def test_snapshot_reads_race_free_and_json_safe(self, clock):
        """Satellite: breaker state is read by background SnapshotWriter
        threads while serving threads mutate it — the snapshot must be a
        consistent, strict-JSON-safe copy."""
        import threading

        stop = threading.Event()
        errs = []

        def reader():
            while not stop.is_set():
                try:
                    json.dumps(guarded.breaker_snapshot(),
                               allow_nan=False)
                    json.dumps(guarded.demoted_sites())
                except Exception as e:  # noqa: BLE001
                    errs.append(e)

        th = threading.Thread(target=reader, daemon=True)
        th.start()
        for i in range(50):
            site = f"sh.race{i % 4}"
            guarded.guarded_call(site, _boom, lambda: "fb")
            clock["t"] += 31.0
            guarded.guarded_call(site, lambda: "kern", lambda: "fb")
        stop.set()
        th.join(5)
        assert not errs


class TestProbeShards:
    @pytest.fixture
    def sharded_idx(self):
        import jax
        from jax.sharding import Mesh

        from raft_tpu.parallel import sharded_ann

        devs = jax.devices()
        mesh = Mesh(np.array((devs * 2)[:2]), ("shard",))
        rng = np.random.default_rng(5)
        return sharded_ann.ShardedCagra(
            mesh, data=rng.standard_normal((2, 8, 4)).astype(np.float32),
            graphs=np.zeros((2, 8, 2), np.int32),
            bases=np.array([0, 5], np.int32),
            counts=np.array([5, 3], np.int32), n_total=8,
            metric=sharded_ann.DistanceType.L2Expanded)

    def test_probe_restores_marked_dead_shard(self, sharded_idx):
        from raft_tpu.parallel import sharded_ann

        idx = sharded_idx
        idx.mark_shard_failed(1)
        # the armed fault keeps the shard dead (the drillable hold)
        with faults.inject("shard_dead",
                           "sharded_ann.cagra.shard1") as f:
            assert sharded_ann.probe_shards(idx) == {1: False}
            assert not idx.shards_ok[1]
            assert idx.last_probe[1]["ok"] is False
            assert "shard fault armed" in idx.last_probe[1]["error"]
            # the canary checks the fault WITHOUT consuming a firing: a
            # background probe tick must not drain a count-limited
            # budget armed for the search path
            assert f.fires == 0
        # fault cleared: the canary succeeds and flips shards_ok back
        assert sharded_ann.probe_shards(idx) == {1: True}
        assert idx.shards_ok[1] and idx.last_probe[1]["ok"] is True
        restored = events.recent(kind="shard_restored")
        assert restored and restored[-1]["site"] \
            == "sharded_ann.cagra.shard1"
        assert restored[-1]["served_frac"] == 1.0
        # healthy shards are never re-probed
        assert sharded_ann.probe_shards(idx) == {}
        # the ops surface carries the per-shard probe verdicts (one
        # entry per live index, aligned with the shards_ok lists)
        snap = sharded_ann.ops_snapshot()
        assert any(p.get("1", {}).get("ok") is True
                   for p in snap["families"]["cagra"]["last_probe"])
        text = debugz.render_text(registry=metrics.Registry())
        assert "shard1 probe: ok" in text

    def test_probe_all_and_snapshot_writer_hook(self, sharded_idx,
                                                tmp_path):
        from raft_tpu.parallel import sharded_ann

        idx = sharded_idx
        idx.mark_shard_failed(0)
        w = debugz.SnapshotWriter(str(tmp_path / "z.json"),
                                  hooks=[sharded_ann.probe_all])
        w.tick()          # one maintenance tick, no thread needed
        assert idx.shards_ok.all()
        # a raising hook must not break the tick
        debugz.SnapshotWriter(str(tmp_path / "z2.json"),
                              hooks=[_boom, lambda: None]).tick()

    def test_single_row_shard_is_probeable(self):
        """A shard whose canary source has one row must still pass its
        probe (the row clamp rounds DOWN, never up past the source)."""
        import jax
        from jax.sharding import Mesh

        from raft_tpu.parallel import sharded_ann

        devs = jax.devices()
        mesh = Mesh(np.array((devs * 2)[:2]), ("shard",))
        idx = sharded_ann.ShardedCagra(
            mesh, data=np.ones((2, 1, 4), np.float32),
            graphs=np.zeros((2, 1, 2), np.int32),
            bases=np.array([0, 1], np.int32),
            counts=np.array([1, 1], np.int32), n_total=2,
            metric=sharded_ann.DistanceType.L2Expanded)
        idx.mark_shard_failed(0)
        assert sharded_ann.probe_shards(idx) == {0: True}
        assert idx.shards_ok.all()

    def test_failed_canary_counts_and_keeps_flag(self, sharded_idx):
        from raft_tpu.parallel import sharded_ann

        idx = sharded_idx
        idx.mark_shard_failed(1)
        before = metrics.counter("sharded.probe_failures.cagra").value

        def bad_probe(index, i):
            raise RuntimeError("device gone")

        assert sharded_ann.probe_shards(idx, probe_fn=bad_probe) \
            == {1: False}
        assert not idx.shards_ok[1]
        assert metrics.counter("sharded.probe_failures.cagra").value \
            == before + 1
        assert "device gone" in idx.last_probe[1]["error"]
        idx.mark_shard_failed(1, ok=True)


class TestBrownout:
    def _rep(self, lat="ok", recall="ok", samples=0, note=None):
        r = {"targets": {
            "p99_latency_s": {"verdict": lat},
            "recall": {"verdict": recall, "samples": samples}}}
        if note:
            r["targets"]["recall"]["note"] = note
        return r

    def test_ladder_steps_hysteresis_and_floor(self):
        reg = metrics.Registry()
        now = {"t": 0.0}
        ctl = BrownoutController(
            [{"max_wait_scale": 2.0, "n_probes": 12},
             {"max_wait_scale": 4.0, "n_probes": 6}],
            registry=reg, min_dwell_s=5.0, up_after_s=15.0,
            clock=lambda: now["t"])
        assert ctl.on_report(self._rep()) == 0
        now["t"] = 10.0
        assert ctl.on_report(self._rep(lat="breach")) == 1
        assert ctl.max_wait_scale() == 2.0
        # hysteresis: a second breach inside min_dwell does not step
        now["t"] = 12.0
        assert ctl.on_report(self._rep(lat="breach")) == 1
        now["t"] = 20.0
        assert ctl.on_report(self._rep(lat="breach")) == 2
        # floor guard: latency still burning but the sentinel sees
        # recall AT the floor -> refuse further degradation...
        now["t"] = 30.0
        assert ctl.on_report(self._rep(lat="breach", recall="warn",
                                       samples=8)) == 2
        # ...and a recall BREACH steps back up even mid-overload — and
        # even INSIDE the dwell window (t=32 is 2s after a refused step
        # attempt window): quality never waits out the hysteresis
        now["t"] = 32.0
        ctl._last_step_at = 31.0     # pin a fresh step for the dwell test
        assert ctl.on_report(self._rep(lat="breach", recall="breach",
                                       samples=8)) == 1
        now["t"] = 40.0
        # a sustained latency WARN is not green: the recovery timer
        # must not accrue while one window still violates (stepping up
        # mid-warn flaps straight back into the breach)
        for t in (50.0, 60.0, 70.0):
            now["t"] = t
            assert ctl.on_report(self._rep(lat="warn")) == 1
        # sustained green steps up toward baseline
        for t in (80.0, 90.0):
            now["t"] = t
            assert ctl.on_report(self._rep()) == 1
        now["t"] = 96.0
        assert ctl.on_report(self._rep()) == 0
        # every transition is an event + a gauge move + in the snapshot
        evs = events.recent(kind="brownout")
        arcs = [(e["level_from"], e["level_to"], e["reason"]) for e in evs]
        assert arcs == [(0, 1, "latency"), (1, 2, "latency"),
                        (2, 1, "recall_floor"), (1, 0, "recovered")]
        assert reg.snapshot()["gauges"]["serve.brownout.level"] == 0
        snap = ctl.snapshot()
        assert len(snap["transitions"]) == 4
        json.dumps(snap, allow_nan=False)

    def test_insufficient_samples_does_not_block_stepdown(self):
        """No sentinel samples = the floor is unwatched; the latency
        ladder still works (the guard only bites when recall is
        MEASURED at the floor)."""
        ctl = BrownoutController(registry=metrics.Registry(),
                                 min_dwell_s=0.0)
        assert ctl.on_report(self._rep(
            lat="breach", recall="ok", samples=0,
            note="insufficient_samples")) == 1

    def test_params_and_searcher_application(self):
        from raft_tpu.neighbors import cagra, ivf_flat

        ctl = BrownoutController(
            [{"n_probes": 8, "itopk_size": 32, "max_wait_scale": 2.0}],
            registry=metrics.Registry(), min_dwell_s=0.0)
        base_f = ivf_flat.SearchParams(n_probes=40)
        base_c = cagra.SearchParams(itopk_size=64)
        assert ctl.params(base_f) is base_f          # level 0: untouched
        ctl.on_report(self._rep(lat="breach"))
        assert ctl.params(base_f).n_probes == 8
        # unknown keys are ignored per family (one ladder, many families)
        assert ctl.params(base_c).itopk_size == 32
        assert ctl.params(base_c).search_width \
            == base_c.search_width

    def test_poll_evaluates_installed_slo(self):
        reg = metrics.Registry()
        eng = slo.SLOEngine(slo.Targets(max_shed_rate=0.1), registry=reg,
                            name="u", fast_window_s=1.0, slow_window_s=1.0)
        ctl = BrownoutController(slo=eng, registry=reg, min_dwell_s=0.0)
        rep = ctl.poll()
        assert rep["brownout_level"] == 0 and "targets" in rep

    def test_debugz_brownout_section(self):
        reg = metrics.Registry()
        ctl = BrownoutController(registry=reg, min_dwell_s=0.0).install()
        try:
            ctl.on_report(self._rep(lat="breach"))
            s = debugz.snapshot(registry=reg)
            assert s["brownout"]["level"] == 1
            json.dumps(s, allow_nan=False)
            assert "brownout (level 1" in debugz.render_text(registry=reg)
        finally:
            degrade.uninstall()


class TestScenario:
    def test_timed_arm_hold_clear(self):
        now = {"t": 0.0}
        sc = (faults.Scenario(clock=lambda: now["t"])
              .add("kernel_fault", "sc.*", at_s=0.0, until_s=5.0)
              .add("shard_dead", "*.shard1", at_s=1.0, until_s=5.0)
              .start())
        assert faults.fired("kernel_fault", "sc.a") is not None
        assert faults.fired("shard_dead", "x.shard1") is None
        now["t"] = 1.5
        assert sc.step() == ["armed shard_dead@*.shard1"]
        assert faults.fired("shard_dead", "x.shard1") is not None
        now["t"] = 5.0
        assert len(sc.step()) == 2 and sc.finished()
        assert faults.fired("kernel_fault", "sc.a") is None
        # the scenario's own stages are fully disarmed (env-armed faults
        # from the ambient lane may still be active — not ours)
        assert not any(f.kind in ("kernel_fault", "shard_dead")
                       for f in faults.active())
        acts = [(e["site"], e["action"])
                for e in events.recent(kind="fault_scenario")]
        assert acts == [("kernel_fault@sc.*", "armed"),
                        ("shard_dead@*.shard1", "armed"),
                        ("kernel_fault@sc.*", "cleared"),
                        ("shard_dead@*.shard1", "cleared")]

    def test_stop_clears_held_stages(self):
        now = {"t": 0.0}
        with faults.Scenario(clock=lambda: now["t"]).add("io_error") as sc:
            assert faults.fired("io_error", "x") is not None
            assert not sc.finished()     # held until stop
        assert faults.fired("io_error", "x") is None
        with pytest.raises(ValueError):
            faults.Scenario().add("x", at_s=5.0, until_s=1.0)


@pytest.mark.slow
class TestChaosDrill:
    """ISSUE 10 acceptance: one end-to-end chaos drill — injected kernel
    fault + dead shard + latency overload produce open breakers, partial
    serve, and a brownout step-down; clearing the faults produces
    breaker re-close, shards_ok restoration, and a ladder step back to
    baseline — every transition trace-stamped, the recall sentinel back
    above the floor, the whole arc readable from one debugz snapshot."""

    DIM = 16

    def test_full_recovery_arc(self, monkeypatch, tmp_path):
        import jax

        from ann_utils import naive_knn
        from raft_tpu.neighbors import brute_force, cagra
        from raft_tpu.parallel import sharded_ann
        from raft_tpu.serve.batcher import BucketLadder, MicroBatcher
        from raft_tpu.serve.quality import RecallSentinel

        if any(f.kind in ("kernel_compile", "kernel_fault")
               for f in faults.active()):
            pytest.skip("ambient kernel faults would re-open the drill "
                        "breaker")
        monkeypatch.setenv("RAFT_TPU_AUTOTUNE_CACHE", "")
        # breaker clock is virtual so probation is instant when stepped
        gnow = {"t": 0.0}
        monkeypatch.setattr(guarded, "_clock", lambda: gnow["t"])

        rng = np.random.default_rng(13)
        centers = rng.standard_normal((8, self.DIM)).astype(np.float32) * 4
        labels = rng.integers(0, 8, size=400)
        data = (centers[labels]
                + rng.standard_normal((400, self.DIM))).astype(np.float32)
        q = (centers[rng.integers(0, 8, size=200)]
             + rng.standard_normal((200, self.DIM))).astype(np.float32)

        index = cagra.build(data, cagra.IndexParams(
            graph_degree=8, intermediate_graph_degree=16, seed=0,
            seed_nodes=0))
        stale = brute_force.build(jax.numpy.asarray(data[:100]))

        reg = metrics.Registry()
        ctl = BrownoutController(
            [{"max_wait_scale": 2.0}],
            registry=reg, min_dwell_s=0.0, up_after_s=0.05).install()
        good = cagra.make_searcher(
            index, cagra.SearchParams(itopk_size=32), degrade=ctl)

        def serving(queries, k, res=None):
            return guarded.guarded_call(
                "drill.selfheal.search",
                lambda: good(queries, k, res),
                lambda: brute_force.search(stale, queries, k))

        sentinel = RecallSentinel(
            lambda qq, kk: naive_knn(np.asarray(data), np.asarray(qq), kk),
            sample=1.0, floor=0.7, window=6, min_samples=3,
            max_pending=64, registry=reg, family="cagra")
        eng = slo.SLOEngine(
            slo.Targets(p99_latency_s=0.05, recall_floor=0.7,
                        recall_family="cagra", recall_min_samples=3),
            registry=reg, name="serve", fast_window_s=0.2,
            slow_window_s=0.4)
        ctl._slo = eng
        # the dead shard half of the blast radius (handmade: the drill
        # exercises mark -> probe-held-down -> restore, not shard_map)
        mesh = jax.sharding.Mesh(np.array((jax.devices() * 2)[:2]),
                                 ("shard",))
        sidx = sharded_ann.ShardedCagra(
            mesh, data=rng.standard_normal((2, 8, 4)).astype(np.float32),
            graphs=np.zeros((2, 8, 2), np.int32),
            bases=np.array([0, 5], np.int32),
            counts=np.array([5, 3], np.int32), n_total=8,
            metric=sharded_ann.DistanceType.L2Expanded)

        b = MicroBatcher(serving, self.DIM,
                         ladder=BucketLadder((8,), (8,)), registry=reg,
                         max_wait_s=0.001, sentinel=sentinel, degrade=ctl)
        snaps = []
        try:
            # ---- phase A: healthy baseline ----
            for j in range(6):
                b.search(q[8 * j: 8 * (j + 1)], 8, timeout=120)
            assert sentinel.drain(60)
            assert sentinel.estimate("cagra") >= 0.75
            eng.evaluate()
            assert ctl.level == 0

            # ---- phase B: chaos — kernel fault + dead shard +
            # overload, held by one timed scenario ----
            sc = (faults.Scenario()
                  .add("kernel_fault", "drill.selfheal.search")
                  .add("shard_dead", "sharded_ann.cagra.shard1")
                  .add("slow_dispatch", "serve.batch", value=0.08)
                  .start())
            sidx.mark_shard_failed(1)
            assert sharded_ann.probe_shards(sidx) == {1: False}
            for j in range(6, 12):
                b.search(q[8 * j: 8 * (j + 1)], 8, timeout=120)
            assert sentinel.drain(60)
            # breaker open on the injected kernel fault; partial serve
            assert "drill.selfheal.search" in guarded.demoted_sites()
            assert guarded.breaker_snapshot()[
                "drill.selfheal.search"]["injected"]
            assert not sidx.shards_ok[1]
            assert sharded_ann.health(sidx)["served_frac"] < 1.0
            # recall collapsed through the stale fallback; SLO breaches;
            # the brownout ladder steps down on the latency breach
            assert sentinel.estimate("cagra") < 0.6
            rep = eng.evaluate()
            assert rep["targets"]["recall"]["verdict"] == "breach"
            assert rep["targets"]["p99_latency_s"]["verdict"] == "breach"
            ctl.on_report({"targets": {
                "p99_latency_s": rep["targets"]["p99_latency_s"]}})
            assert ctl.level == 1 and ctl.max_wait_scale() == 2.0
            snaps.append(debugz.snapshot(batcher=b, registry=reg, slo=eng))

            # ---- phase C: faults clear; probes close the loop ----
            sc.stop()
            assert sharded_ann.probe_all() == {"cagra": {1: True}}
            assert sidx.shards_ok[1]
            gnow["t"] += 3600.0          # probation long over
            for j in range(12, 20):
                b.search(q[8 * j: 8 * (j + 1)], 8, timeout=120)
            assert sentinel.drain(60)
            # the first post-clear dispatch probed and re-closed
            assert "drill.selfheal.search" not in guarded.demoted_sites()
            assert guarded.breaker_snapshot()[
                "drill.selfheal.search"]["state"] == "closed"
            # quality restored above the floor
            assert sentinel.estimate("cagra") >= 0.75
            rep = eng.evaluate()
            assert rep["targets"]["recall"]["verdict"] == "ok"
            # sustained green steps the ladder back to baseline
            time.sleep(0.1)
            ctl.on_report(self._ok_report())
            time.sleep(0.1)
            ctl.on_report(self._ok_report())
            assert ctl.level == 0
            snaps.append(debugz.snapshot(batcher=b, registry=reg, slo=eng))
        finally:
            b.close()
            sentinel.close()
            degrade.uninstall()
            slo.uninstall()

        # ---- the whole arc is on the record, strict-JSON end to end ----
        kinds = [e["kind"] for e in events.recent()]
        for kind in ("fault_scenario", "breaker_open", "shard_marked",
                     "recall_regression", "slo_breach", "brownout",
                     "shard_restored", "breaker_probe", "breaker_close"):
            assert kind in kinds, f"missing {kind} in the flight recorder"
        # ordering: open before probe before close; restore after mark
        assert kinds.index("breaker_open") < kinds.index("breaker_probe") \
            < kinds.index("breaker_close")
        degraded, healthy = snaps
        assert degraded["breakers"]["drill.selfheal.search"]["state"] \
            == "open"
        assert degraded["brownout"]["level"] == 1
        assert degraded["slo"]["verdict"] == "breach"
        assert degraded["sharded"]["families"]["cagra"]["shards_ok"][-1] \
            == [True, False]
        assert healthy["breakers"]["drill.selfheal.search"]["state"] \
            == "closed"
        assert healthy["brownout"]["level"] == 0
        assert any(p.get("1", {}).get("ok") is True for p in
                   healthy["sharded"]["families"]["cagra"]["last_probe"])
        for s in snaps:
            json.dumps(s, allow_nan=False)
        path = tmp_path / "drill.jsonl"
        assert events.export_jsonl(str(path)) > 0

    @staticmethod
    def _ok_report():
        return {"targets": {"p99_latency_s": {"verdict": "ok"},
                            "recall": {"verdict": "ok", "samples": 8}}}
