"""Clustering tests (analog of CLUSTER_TEST)."""
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu.cluster import kmeans, kmeans_balanced
from raft_tpu.cluster.kmeans import InitMethod, KMeansParams


def _blobs(rng, n_per=200, k=5, d=8, spread=0.15):
    centers = rng.standard_normal((k, d)).astype(np.float32) * 3
    pts = np.concatenate([
        c + spread * rng.standard_normal((n_per, d)).astype(np.float32)
        for c in centers
    ])
    labels = np.repeat(np.arange(k), n_per)
    perm = rng.permutation(len(pts))
    return pts[perm], labels[perm], centers


def _purity(found_labels, true_labels, k):
    """Fraction of points whose cluster's majority true-label matches."""
    total = 0
    for c in range(k):
        members = true_labels[found_labels == c]
        if len(members):
            total += np.bincount(members).max()
    return total / len(true_labels)


class TestKMeans:
    def test_recovers_blobs(self, rng):
        x, true, _ = _blobs(rng)
        params = KMeansParams(n_clusters=5, max_iter=50, seed=1)
        centers, inertia, n_iter = kmeans.fit(x, params)
        labels, _ = kmeans.predict(x, centers)
        assert _purity(np.asarray(labels), true, 5) > 0.99
        assert int(n_iter) < 50  # converged before cap

    def test_plus_plus_beats_bad_random(self, rng):
        x, _, _ = _blobs(rng, k=8, spread=0.05)
        pp = kmeans.fit(x, KMeansParams(n_clusters=8, init=InitMethod.KMeansPlusPlus,
                                        max_iter=2, seed=0))[1]
        rnd = kmeans.fit(x, KMeansParams(n_clusters=8, init=InitMethod.Random,
                                         max_iter=2, seed=0))[1]
        assert float(pp) <= float(rnd) * 1.5

    def test_init_array(self, rng):
        x, _, centers = _blobs(rng)
        c, inertia, _ = kmeans.fit(
            x, KMeansParams(n_clusters=5, init=InitMethod.Array, max_iter=20),
            centroids=centers)
        labels, _ = kmeans.predict(x, c)
        assert len(np.unique(np.asarray(labels))) == 5

    def test_transform_and_cost(self, rng):
        x, _, _ = _blobs(rng, k=3)
        centers, inertia, _ = kmeans.fit(x, KMeansParams(n_clusters=3, seed=0))
        t = kmeans.transform(x, centers)
        assert t.shape == (x.shape[0], 3)
        cost = kmeans.cluster_cost(x, centers)
        np.testing.assert_allclose(float(cost), float(inertia), rtol=1e-3)
        np.testing.assert_allclose(float(cost), float(np.asarray(t).min(1).sum()),
                                   rtol=1e-3)

    def test_mini_batch(self, rng):
        x, true, _ = _blobs(rng, n_per=400, k=4)
        params = KMeansParams(n_clusters=4, max_iter=30, seed=0, batch_samples=256)
        centers, inertia, _ = kmeans.fit_mini_batch(x, params)
        labels, _ = kmeans.predict(x, centers)
        assert _purity(np.asarray(labels), true, 4) > 0.95

    def test_n_init_picks_best(self, rng):
        x, _, _ = _blobs(rng, k=6)
        one = kmeans.fit(x, KMeansParams(n_clusters=6, max_iter=30, seed=0, n_init=1))[1]
        three = kmeans.fit(x, KMeansParams(n_clusters=6, max_iter=30, seed=0, n_init=3))[1]
        assert float(three) <= float(one) + 1e-3

    def test_compute_new_centroids_decreases_cost(self, rng):
        x, _, _ = _blobs(rng, k=4)
        centers = kmeans.init_plus_plus(x, 4, seed=3)
        before = float(kmeans.cluster_cost(x, centers))
        stepped = kmeans.compute_new_centroids(x, centers)
        after = float(kmeans.cluster_cost(x, stepped))
        assert after <= before + 1e-5
        # explicit labels give the same update as recomputed labels
        labels, _ = kmeans.predict(x, centers)
        np.testing.assert_allclose(
            np.asarray(kmeans.compute_new_centroids(x, centers, labels)),
            np.asarray(stepped), rtol=1e-6)
        from raft_tpu.core.errors import RaftError
        with pytest.raises(RaftError):  # labels from a different k
            kmeans.compute_new_centroids(x, centers, np.full(len(x), 9))


class TestBalanced:
    def test_balance_quality(self, rng):
        x = rng.standard_normal((6000, 16)).astype(np.float32)
        k = 64
        centers = kmeans_balanced.fit(x, k)
        labels, _ = kmeans_balanced.predict(x, centers)
        counts = np.bincount(np.asarray(labels), minlength=k)
        assert counts.min() > 0, "no empty clusters"
        avg = 6000 / k
        # balanced trainer should keep sizes within a reasonable envelope
        assert counts.max() < 4 * avg
        assert (counts > avg / 4).mean() > 0.9

    def test_small_k(self, rng):
        x, true, _ = _blobs(rng, k=3)
        centers = kmeans_balanced.fit(x, 3)
        labels, _ = kmeans_balanced.predict(x, centers)
        assert _purity(np.asarray(labels), true, 3) > 0.95

    def test_clustered_data(self, rng):
        x, true, _ = _blobs(rng, n_per=300, k=10, d=12)
        centers, labels = kmeans_balanced.fit_predict(x, 32)
        counts = np.bincount(np.asarray(labels), minlength=32)
        assert counts.min() > 0
        # inertia sanity: points should be close to their centers
        _, d2 = kmeans_balanced.predict(x, centers)
        assert float(jnp.mean(d2)) < float(jnp.var(jnp.asarray(x)) * x.shape[1])


class TestAutoFindK:
    def test_recovers_blob_count(self):
        from raft_tpu import random as rrnd
        from raft_tpu.cluster import kmeans

        x, _ = rrnd.make_blobs(600, 8, n_clusters=4, cluster_std=0.3, rng=3)
        best_k, centers, labels = kmeans.auto_find_k(np.asarray(x), 2, 8)
        assert best_k == 4
        assert centers.shape == (4, 8)
        assert len(np.unique(np.asarray(labels))) == 4
