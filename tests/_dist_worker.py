"""Worker process for the 2-process jax.distributed smoke test.

Role of a raft-dask worker in test_comms.py:69-338: join the clique via
the coordinator (the ncclUniqueId-broadcast analog), run the collective
self-tests through the injected comms, then a sharded brute-force search,
and print a checkable verdict. Invoked by test_distributed.py as

    python tests/_dist_worker.py <coordinator> <n_procs> <rank>
"""
import os
import sys

# each process contributes 2 virtual CPU devices to the global clique
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=2").strip()

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")


def main(coordinator: str, n_procs: int, rank: int) -> None:
    import jax.numpy as jnp
    import numpy as np

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from raft_tpu.comms import bootstrap

    # bootstrap FIRST: jax.distributed.initialize must run before anything
    # touches the XLA backend (Resources eagerly derives a PRNG key)
    mesh, comms = bootstrap.init_comms(
        coordinator_address=coordinator, num_processes=n_procs,
        process_id=rank, axis="shard")
    from raft_tpu.core import Resources

    res = Resources(seed=0)
    res.set_comms(comms)
    n_dev = len(jax.devices())
    assert n_dev == 2 * n_procs, f"global devices {n_dev}"
    assert res.has_comms()

    # collective self-test (comms_test.hpp analog) over the global mesh
    from raft_tpu.comms.comms_test import run_all

    results = run_all(mesh)
    failed = [name for name, ok in results.items() if not ok]
    assert not failed, f"collective self-tests failed: {failed}"

    # sharded brute-force search over the global device clique
    from raft_tpu.parallel import sharded_knn

    rng = np.random.default_rng(0)
    data = rng.standard_normal((1000, 16)).astype(np.float32)
    q = rng.standard_normal((8, 16)).astype(np.float32)
    index = sharded_knn.build(data, mesh)
    d, i = sharded_knn.search(index, q, k=5, algo="scan")
    jax.block_until_ready((d, i))
    # verify against the local exact answer (deterministic on every rank)
    from raft_tpu.neighbors import brute_force

    _, want = brute_force.search(brute_force.build(data), q, 5, algo="scan")
    np.testing.assert_array_equal(np.asarray(i), np.asarray(want))
    print(f"DIST_WORKER_OK rank={rank} devices={n_dev}", flush=True)


if __name__ == "__main__":
    main(sys.argv[1], int(sys.argv[2]), int(sys.argv[3]))
