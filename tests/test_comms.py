"""Comms protocol + self-test battery on the 8-device CPU mesh — the
LocalCUDACluster-style distributed test (raft_dask/test/test_comms.py
analog, running real collectives through shard_map)."""
import jax
import numpy as np
import pytest

from raft_tpu.comms import AxisComms, comms_test, init_comms, local_mesh
from raft_tpu.core.resources import Resources
from raft_tpu.utils import shard_map_compat


@pytest.fixture(scope="module")
def mesh():
    return local_mesh(8)


def test_selftest_battery(mesh):
    results = comms_test.run_all(mesh)
    assert all(results.values()), results


def test_comm_split_groups(mesh):
    results = comms_test.test_commsplit(mesh, 4)
    assert results


def test_init_comms_injects_into_resources(mesh):
    res = Resources()
    got_mesh, comms = init_comms(n_devices=8, resources=res)
    assert res.has_comms() and res.comms is comms
    assert comms.get_size() == 8
    assert got_mesh.devices.size == 8


def test_allgatherv_and_gatherv(mesh):
    import functools
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    axis = mesh.axis_names[0]
    comms = AxisComms(axis, size=8)
    counts = [3, 1, 2, 3, 1, 2, 3, 1]

    def body():
        rank = comms.get_rank()
        row = jnp.where(jnp.arange(3) < jnp.asarray(counts)[rank],
                        rank.astype(jnp.float32), jnp.nan)
        g, c = comms.allgatherv(row, counts)
        # each rank's valid prefix must hold its rank id
        ok = jnp.float32(1.0)
        for r in range(8):
            valid = jnp.arange(3) < c[r]
            ok = ok * jnp.all(jnp.where(valid, g[r] == r, True))
        return comms.allreduce(ok)

    shmap = shard_map_compat(body, mesh=mesh, in_specs=(), out_specs=P(),
                          check=False)
    assert float(np.asarray(jax.jit(shmap)())) == 8.0


def test_allgatherv_counts_masked_reduction(mesh):
    """The padded-dense contract's load-bearing half: padding slots hold
    garbage (NaN here), and a counts-masked reduction over the gathered
    axis must still produce the exact ragged answer (the raft-dask
    comms_utils.pyx:42-78 allgatherv consumer pattern)."""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    axis = mesh.axis_names[0]
    comms = AxisComms(axis, size=8)
    counts = [3, 1, 2, 3, 1, 2, 3, 1]
    # true ragged sum: each rank contributes counts[r] rows of value r+1
    want = sum((r + 1) * c for r, c in enumerate(counts))

    def body():
        rank = comms.get_rank()
        row = jnp.where(jnp.arange(3) < jnp.asarray(counts)[rank],
                        (rank + 1).astype(jnp.float32), jnp.nan)
        g, c = comms.allgatherv(row, counts)
        # unmasked reduction would be NaN — the mask is what the
        # contract requires of callers
        mask = jnp.arange(3)[None, :] < c[:, None]
        return jnp.sum(jnp.where(mask, g, 0.0))

    shmap = shard_map_compat(body, mesh=mesh, in_specs=(), out_specs=P(),
                          check=False)
    got = float(np.asarray(jax.jit(shmap)()))
    assert got == float(want), (got, want)


def test_multicast_sendrecv(mesh):
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    axis = mesh.axis_names[0]
    comms = AxisComms(axis, size=8)

    def body():
        rank = comms.get_rank().astype(jnp.float32)
        got = comms.device_multicast_sendrecv(rank, dests=[1, 2])
        want1 = (comms.get_rank() - 1) % 8
        want2 = (comms.get_rank() - 2) % 8
        ok = (got[0] == want1) & (got[1] == want2)
        return comms.allreduce(ok.astype(jnp.float32))

    shmap = shard_map_compat(body, mesh=mesh, in_specs=(), out_specs=P(),
                          check=False)
    assert float(np.asarray(jax.jit(shmap)())) == 8.0


# -- bootstrap (raft_tpu.comms.bootstrap): env autodetect + idempotence --

from raft_tpu.comms import bootstrap  # noqa: E402
from raft_tpu.core.errors import RaftError  # noqa: E402


class TestBootstrapResolve:
    def test_no_config_is_single_process(self):
        assert bootstrap._resolve_env(environ={}) == {"distributed": False}

    def test_full_env_autodetect(self):
        env = {"RAFT_TPU_COORDINATOR": "127.0.0.1:1234",
               "RAFT_TPU_NUM_PROCESSES": "2",
               "RAFT_TPU_PROCESS_ID": "1"}
        assert bootstrap._resolve_env(environ=env) == {
            "distributed": True,
            "coordinator_address": "127.0.0.1:1234",
            "num_processes": 2, "process_id": 1}

    def test_jax_env_fallback(self):
        env = {"JAX_COORDINATOR_ADDRESS": "127.0.0.1:9",
               "JAX_NUM_PROCESSES": "2", "JAX_PROCESS_ID": "0"}
        assert bootstrap._resolve_env(environ=env)["distributed"]

    def test_args_win_over_env(self):
        env = {"RAFT_TPU_COORDINATOR": "env-host:1",
               "RAFT_TPU_NUM_PROCESSES": "4",
               "RAFT_TPU_PROCESS_ID": "3"}
        cfg = bootstrap._resolve_env("arg-host:2", environ=env)
        assert cfg["coordinator_address"] == "arg-host:2"
        assert (cfg["num_processes"], cfg["process_id"]) == (4, 3)

    def test_partial_config_raises_naming_missing(self):
        """A partial spec would otherwise hang at the first collective —
        the error must name what is set and what is missing."""
        env = {"RAFT_TPU_COORDINATOR": "127.0.0.1:1234"}
        with pytest.raises(RaftError) as ei:
            bootstrap._resolve_env(environ=env)
        msg = str(ei.value)
        assert "coordinator_address" in msg
        assert "num_processes" in msg and "process_id" in msg
        assert "RAFT_TPU_NUM_PROCESSES" in msg

    def test_bad_values_raise(self):
        with pytest.raises(RaftError):
            bootstrap._resolve_env(environ={
                "RAFT_TPU_COORDINATOR": "c",
                "RAFT_TPU_NUM_PROCESSES": "nope",
                "RAFT_TPU_PROCESS_ID": "0"})
        with pytest.raises(RaftError):   # rank out of range
            bootstrap._resolve_env("c", 2, 5, environ={})
        with pytest.raises(RaftError):
            bootstrap._resolve_env("c", 0, 0, environ={})

    def test_idempotent_reinit_guard(self, monkeypatch):
        """Same triple: no-op with already=True. Different triple:
        refused — one process is one rank for life. (The module state is
        pre-seeded; jax.distributed.initialize is never called.)"""
        triple = ("127.0.0.1:7777", 2, 0)
        monkeypatch.setattr(bootstrap, "_initialized", triple)
        cfg = bootstrap.init_distributed(*triple)
        assert cfg.get("already") is True and cfg["process_id"] == 0
        with pytest.raises(RaftError):
            bootstrap.init_distributed("127.0.0.1:7777", 2, 1)

    def test_single_process_passthrough(self, monkeypatch):
        monkeypatch.setattr(bootstrap, "_initialized", None)
        for name in ("RAFT_TPU_COORDINATOR", "RAFT_TPU_NUM_PROCESSES",
                     "RAFT_TPU_PROCESS_ID", "JAX_COORDINATOR_ADDRESS",
                     "JAX_NUM_PROCESSES", "JAX_PROCESS_ID"):
            monkeypatch.delenv(name, raising=False)
        assert bootstrap.init_distributed() == {"distributed": False}
        assert bootstrap._initialized is None
