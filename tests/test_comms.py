"""Comms protocol + self-test battery on the 8-device CPU mesh — the
LocalCUDACluster-style distributed test (raft_dask/test/test_comms.py
analog, running real collectives through shard_map)."""
import jax
import numpy as np
import pytest

from raft_tpu.comms import AxisComms, comms_test, init_comms, local_mesh
from raft_tpu.core.resources import Resources
from raft_tpu.utils import shard_map_compat


@pytest.fixture(scope="module")
def mesh():
    return local_mesh(8)


def test_selftest_battery(mesh):
    results = comms_test.run_all(mesh)
    assert all(results.values()), results


def test_comm_split_groups(mesh):
    results = comms_test.test_commsplit(mesh, 4)
    assert results


def test_init_comms_injects_into_resources(mesh):
    res = Resources()
    got_mesh, comms = init_comms(n_devices=8, resources=res)
    assert res.has_comms() and res.comms is comms
    assert comms.get_size() == 8
    assert got_mesh.devices.size == 8


def test_allgatherv_and_gatherv(mesh):
    import functools
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    axis = mesh.axis_names[0]
    comms = AxisComms(axis, size=8)
    counts = [3, 1, 2, 3, 1, 2, 3, 1]

    def body():
        rank = comms.get_rank()
        row = jnp.where(jnp.arange(3) < jnp.asarray(counts)[rank],
                        rank.astype(jnp.float32), jnp.nan)
        g, c = comms.allgatherv(row, counts)
        # each rank's valid prefix must hold its rank id
        ok = jnp.float32(1.0)
        for r in range(8):
            valid = jnp.arange(3) < c[r]
            ok = ok * jnp.all(jnp.where(valid, g[r] == r, True))
        return comms.allreduce(ok)

    shmap = shard_map_compat(body, mesh=mesh, in_specs=(), out_specs=P(),
                          check=False)
    assert float(np.asarray(jax.jit(shmap)())) == 8.0


def test_allgatherv_counts_masked_reduction(mesh):
    """The padded-dense contract's load-bearing half: padding slots hold
    garbage (NaN here), and a counts-masked reduction over the gathered
    axis must still produce the exact ragged answer (the raft-dask
    comms_utils.pyx:42-78 allgatherv consumer pattern)."""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    axis = mesh.axis_names[0]
    comms = AxisComms(axis, size=8)
    counts = [3, 1, 2, 3, 1, 2, 3, 1]
    # true ragged sum: each rank contributes counts[r] rows of value r+1
    want = sum((r + 1) * c for r, c in enumerate(counts))

    def body():
        rank = comms.get_rank()
        row = jnp.where(jnp.arange(3) < jnp.asarray(counts)[rank],
                        (rank + 1).astype(jnp.float32), jnp.nan)
        g, c = comms.allgatherv(row, counts)
        # unmasked reduction would be NaN — the mask is what the
        # contract requires of callers
        mask = jnp.arange(3)[None, :] < c[:, None]
        return jnp.sum(jnp.where(mask, g, 0.0))

    shmap = shard_map_compat(body, mesh=mesh, in_specs=(), out_specs=P(),
                          check=False)
    got = float(np.asarray(jax.jit(shmap)()))
    assert got == float(want), (got, want)


def test_multicast_sendrecv(mesh):
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    axis = mesh.axis_names[0]
    comms = AxisComms(axis, size=8)

    def body():
        rank = comms.get_rank().astype(jnp.float32)
        got = comms.device_multicast_sendrecv(rank, dests=[1, 2])
        want1 = (comms.get_rank() - 1) % 8
        want2 = (comms.get_rank() - 2) % 8
        ok = (got[0] == want1) & (got[1] == want2)
        return comms.allreduce(ok.astype(jnp.float32))

    shmap = shard_map_compat(body, mesh=mesh, in_specs=(), out_specs=P(),
                          check=False)
    assert float(np.asarray(jax.jit(shmap)())) == 8.0
