"""Oracle tests for the fused Pallas distance+top-k kernel (interpret mode
on CPU; the same code compiles for TPU — the `-m tpu` lane runs it there)."""
import numpy as np
import pytest

from raft_tpu.ops import fused_knn


def _oracle(q, x, metric):
    if metric == "l2":
        return ((q[:, None, :].astype(np.float64) - x[None, :, :]) ** 2).sum(-1)
    if metric == "cos":
        qn = q / np.linalg.norm(q, axis=1, keepdims=True)
        xn = x / np.linalg.norm(x, axis=1, keepdims=True)
        return 1.0 - qn @ xn.T
    return -(q.astype(np.float64) @ x.T.astype(np.float64))


@pytest.mark.parametrize("m,n,d,k,metric", [
    (64, 1000, 32, 10, "l2"),
    (33, 300, 17, 5, "cos"),
    (16, 257, 96, 16, "ip"),
    (8, 2048, 128, 100, "l2"),   # k > tile lane width path
])
def test_fused_knn_oracle(m, n, d, k, metric):
    rng = np.random.default_rng(7)
    q = rng.standard_normal((m, d), dtype=np.float32)
    x = rng.standard_normal((n, d), dtype=np.float32)
    v, i = fused_knn(q, x, k, metric=metric, interpret=True)
    v, i = np.asarray(v), np.asarray(i)
    ref = _oracle(q, x, metric)
    ref_i = np.argsort(ref, axis=1)[:, :k]
    ref_v = np.take_along_axis(ref, ref_i, axis=1)
    np.testing.assert_allclose(v, ref_v, rtol=1e-4, atol=1e-4)
    recall = np.mean([len(set(i[r]) & set(ref_i[r])) / k for r in range(m)])
    assert recall == 1.0


def test_fused_knn_penalty_excludes_rows():
    rng = np.random.default_rng(3)
    q = rng.standard_normal((16, 32), dtype=np.float32)
    x = rng.standard_normal((500, 32), dtype=np.float32)
    pen = np.zeros(500, np.float32)
    pen[::2] = np.inf
    v, i = fused_knn(q, x, 8, penalty=pen, interpret=True)
    assert np.all(np.asarray(i) % 2 == 1)
    assert np.all(np.isfinite(np.asarray(v)))


def test_fused_knn_sparse_survivors_across_tiles():
    """<k unmasked rows spread over multiple tiles: unfilled slots must be
    -1/inf, never a duplicated real id (regression: the inf tie-scan used
    to re-emit column 0's retired id)."""
    rng = np.random.default_rng(9)
    q = rng.standard_normal((4, 64), dtype=np.float32)
    x = rng.standard_normal((2048, 64), dtype=np.float32)
    pen = np.full(2048, np.inf, np.float32)
    pen[[10, 1500]] = 0.0
    v, i = fused_knn(q, x, 3, penalty=pen, interpret=True)
    v, i = np.asarray(v), np.asarray(i)
    assert set(i[:, :2].ravel()) == {10, 1500}
    assert np.all(i[:, 2] == -1) and np.all(np.isinf(v[:, 2]))


def test_fused_knn_k_exceeds_valid_rows():
    """More requested neighbors than admissible rows → +inf / -1 padding."""
    rng = np.random.default_rng(4)
    q = rng.standard_normal((8, 16), dtype=np.float32)
    x = rng.standard_normal((40, 16), dtype=np.float32)
    pen = np.full(40, np.inf, np.float32)
    pen[:5] = 0.0
    v, i = fused_knn(q, x, 10, penalty=pen, interpret=True)
    v, i = np.asarray(v), np.asarray(i)
    assert np.all(np.isfinite(v[:, :5])) and np.all(np.isinf(v[:, 5:]))
    assert set(i[:, :5].ravel()) <= {0, 1, 2, 3, 4}
    assert np.all(i[:, 5:] == -1)


@pytest.mark.parametrize("metric", ["sqeuclidean", "euclidean", "cosine",
                                    "inner_product"])
def test_brute_force_pallas_matches_scan(metric):
    from raft_tpu.neighbors import brute_force

    rng = np.random.default_rng(11)
    x = rng.standard_normal((700, 48), dtype=np.float32)
    q = rng.standard_normal((50, 48), dtype=np.float32)
    index = brute_force.build(x, metric=metric)
    vs, is_ = brute_force.search(index, q, 10, algo="scan")
    vp, ip = brute_force.search(index, q, 10, algo="pallas")
    np.testing.assert_allclose(np.asarray(vp), np.asarray(vs),
                               rtol=1e-4, atol=1e-4)
    agree = np.mean(np.asarray(ip) == np.asarray(is_))
    assert agree > 0.99  # ties may order differently


class TestIvfScanParity:
    """CPU interpret-mode parity for the query-grouped IVF scan kernels —
    the pallas paths must match the XLA gather paths bit-for-bit (flat)
    / to equal quality (PQ) without TPU hardware in the loop."""

    def test_ivf_flat_pallas_matches_xla(self):
        from raft_tpu.neighbors import ivf_flat

        rng = np.random.default_rng(21)
        data = rng.standard_normal((2000, 40), dtype=np.float32)
        q = rng.standard_normal((25, 40), dtype=np.float32)
        for metric in ["sqeuclidean", "cosine", "inner_product"]:
            index = ivf_flat.build(data, ivf_flat.IndexParams(
                n_lists=16, metric=metric, seed=0))
            dx, ix = ivf_flat.search(index, q, 8,
                                     ivf_flat.SearchParams(n_probes=16),
                                     algo="xla")
            dp, ip = ivf_flat.search(index, q, 8,
                                     ivf_flat.SearchParams(n_probes=16),
                                     algo="pallas")
            assert np.mean(np.asarray(ip) == np.asarray(ix)) > 0.99, metric
            np.testing.assert_allclose(np.asarray(dp), np.asarray(dx),
                                       rtol=1e-3, atol=1e-3)

    def test_ivf_flat_pallas_byte_dtypes_match_xla(self):
        """int8 (per-row scales in-kernel) and uint8 (exact bytes) must
        track the XLA gather path through the pallas scan."""
        from raft_tpu.neighbors import ivf_flat

        rng = np.random.default_rng(23)
        data = rng.standard_normal((2000, 40)).astype(np.float32)
        q = rng.standard_normal((25, 40)).astype(np.float32)
        bdata = np.round(np.clip(data * 40 + 128, 0, 255)).astype(np.float32)
        bq = np.round(np.clip(q * 40 + 128, 0, 255)).astype(np.float32)
        for dtype, dd, qq, id_floor in (("int8", data, q, 0.9),
                                        ("uint8", bdata, bq, 0.999)):
            index = ivf_flat.build(dd, ivf_flat.IndexParams(
                n_lists=16, seed=0, dtype=dtype))
            dx, ix = ivf_flat.search(index, qq, 8,
                                     ivf_flat.SearchParams(n_probes=16),
                                     algo="xla")
            dp, ip = ivf_flat.search(index, qq, 8,
                                     ivf_flat.SearchParams(n_probes=16),
                                     algo="pallas")
            match = np.mean(np.asarray(ip) == np.asarray(ix))
            assert match > id_floor, (dtype, match)
            np.testing.assert_allclose(np.asarray(dp), np.asarray(dx),
                                       rtol=5e-2, atol=5e-1)

    @pytest.mark.xfail(
        strict=False, run=False,
        reason="known jax-0.4.37 interpret divergence: pltpu.repeat is "
               "ELEMENT-wise (np.repeat) under the CPU interpreter while "
               "the ivf_pq one-hot decode requires tiling semantics "
               "(see ivf_pq_scan.make_cb_matrix), scrambling the decode "
               "for every lut_mode; expected to pass on the Mosaic "
               "lowering (tiling), pending first real-TPU validation. "
               "run=False: environment-pinned, and the run only burns "
               "the tight tier-1 budget")
    def test_ivf_pq_pallas_matches_xla(self):
        import jax.numpy as jnp

        from raft_tpu.neighbors import ivf_pq

        rng = np.random.default_rng(22)
        data = rng.standard_normal((2000, 32), dtype=np.float32)
        q = rng.standard_normal((25, 32), dtype=np.float32)
        index = ivf_pq.build(data, ivf_pq.IndexParams(
            n_lists=16, pq_dim=8, seed=0))
        # f32 LUT: both engines compute the same quantities exactly, so id
        # agreement is near-total (bf16 LUTs round differently per engine)
        sp = ivf_pq.SearchParams(n_probes=16, lut_dtype=jnp.float32)
        dx, ix = ivf_pq.search(index, q, 8, sp, algo="xla")
        dp, ip = ivf_pq.search(index, q, 8, sp, algo="pallas")
        assert np.mean(np.asarray(ip) == np.asarray(ix)) > 0.95
        # bf16 default: quality must match within tolerance
        spb = ivf_pq.SearchParams(n_probes=16)
        db, ib = ivf_pq.search(index, q, 8, spb, algo="pallas")
        overlap = np.mean([len(set(ib[r].tolist()) & set(ix[r].tolist())) / 8
                           for r in range(len(q))])
        assert overlap > 0.85

    def test_ivf_flat_pallas_filter_matches_xla(self):
        from raft_tpu.core.bitset import Bitset
        from raft_tpu.neighbors import ivf_flat

        rng = np.random.default_rng(31)
        data = rng.standard_normal((1500, 24), dtype=np.float32)
        q = rng.standard_normal((20, 24), dtype=np.float32)
        keep = rng.random(1500) > 0.4
        filt = Bitset.from_mask(keep)
        index = ivf_flat.build(data, ivf_flat.IndexParams(n_lists=12, seed=0))
        sp = ivf_flat.SearchParams(n_probes=12)
        dx, ix = ivf_flat.search(index, q, 8, sp, algo="xla", filter=filt)
        dp, ip = ivf_flat.search(index, q, 8, sp, algo="pallas", filter=filt)
        ip_np = np.asarray(ip)
        assert keep[ip_np[ip_np >= 0]].all()
        assert np.mean(ip_np == np.asarray(ix)) > 0.99
        np.testing.assert_allclose(np.asarray(dp), np.asarray(dx),
                                   rtol=1e-3, atol=1e-3)

    @pytest.mark.xfail(
        strict=False, run=False,
        reason="known jax-0.4.37 interpret divergence: pltpu.repeat is "
               "ELEMENT-wise (np.repeat) under the CPU interpreter while "
               "the ivf_pq one-hot decode requires tiling semantics "
               "(see ivf_pq_scan.make_cb_matrix), scrambling the decode "
               "for every lut_mode; expected to pass on the Mosaic "
               "lowering (tiling), pending first real-TPU validation. "
               "run=False: environment-pinned, and the run only burns "
               "the tight tier-1 budget")
    def test_ivf_pq_pallas_filter_excludes(self):
        import jax.numpy as jnp

        from raft_tpu.core.bitset import Bitset
        from raft_tpu.neighbors import ivf_pq

        rng = np.random.default_rng(32)
        data = rng.standard_normal((1500, 32), dtype=np.float32)
        q = rng.standard_normal((15, 32), dtype=np.float32)
        keep = rng.random(1500) > 0.5
        filt = Bitset.from_mask(keep)
        index = ivf_pq.build(data, ivf_pq.IndexParams(n_lists=12, pq_dim=8,
                                                      seed=0))
        sp = ivf_pq.SearchParams(n_probes=12, lut_dtype=jnp.float32)
        dx, ix = ivf_pq.search(index, q, 8, sp, algo="xla", filter=filt)
        dp, ip = ivf_pq.search(index, q, 8, sp, algo="pallas", filter=filt)
        ip_np = np.asarray(ip)
        assert keep[ip_np[ip_np >= 0]].all()
        assert np.mean(ip_np == np.asarray(ix)) > 0.95

    def test_ivf_flat_pallas_small_k_and_tail_lists(self):
        """k larger than some list sizes + uneven lists: sentinel handling."""
        from raft_tpu.neighbors import ivf_flat

        rng = np.random.default_rng(23)
        data = rng.standard_normal((300, 16), dtype=np.float32)
        q = rng.standard_normal((10, 16), dtype=np.float32)
        index = ivf_flat.build(data, ivf_flat.IndexParams(n_lists=12,
                                                          seed=0))
        d1, i1 = ivf_flat.search(index, q, 5,
                                 ivf_flat.SearchParams(n_probes=1),
                                 algo="pallas")
        i1 = np.asarray(i1)
        assert ((i1 >= -1) & (i1 < 300)).all()


def test_brute_force_pallas_filter():
    from raft_tpu.core.bitset import Bitset
    from raft_tpu.neighbors import brute_force

    rng = np.random.default_rng(12)
    x = rng.standard_normal((300, 32), dtype=np.float32)
    q = rng.standard_normal((20, 32), dtype=np.float32)
    keep = rng.random(300) > 0.5
    bs = Bitset.from_mask(keep)
    index = brute_force.build(x)
    vs, is_ = brute_force.search(index, q, 5, filter=bs, algo="scan")
    vp, ip = brute_force.search(index, q, 5, filter=bs, algo="pallas")
    np.testing.assert_allclose(np.asarray(vp), np.asarray(vs),
                               rtol=1e-4, atol=1e-4)
    assert keep[np.asarray(ip)].all()
