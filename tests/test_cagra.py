"""CAGRA + NN-descent tests (analog of NEIGHBORS_ANN_CAGRA_TEST /
NEIGHBORS_ANN_NN_DESCENT_TEST): recall vs brute-force oracle (SURVEY.md §4)."""
import jax.numpy as jnp
import numpy as np
import pytest

from ann_utils import calc_recall, naive_knn
from raft_tpu.core.bitset import Bitset
from raft_tpu.neighbors import cagra, nn_descent


@pytest.fixture(scope="module")
def dataset():
    rng = np.random.default_rng(7)
    return rng.standard_normal((6_000, 32)).astype(np.float32)


@pytest.fixture(scope="module")
def queries():
    rng = np.random.default_rng(8)
    return rng.standard_normal((100, 32)).astype(np.float32)


@pytest.fixture(scope="module")
def knn_oracle(dataset):
    return naive_knn(dataset, dataset, 33)  # k+1: includes self


@pytest.fixture(scope="module")
def built_index(dataset):
    return cagra.build(dataset, cagra.IndexParams(
        intermediate_graph_degree=64, graph_degree=32, seed=0))


class TestNnDescent:
    @pytest.mark.slow
    def test_graph_quality(self, dataset, knn_oracle):
        k = 32
        graph = nn_descent.build(dataset, k, n_iters=20, seed=0)
        assert graph.shape == (len(dataset), k)
        assert (graph != np.arange(len(dataset))[:, None]).all()  # no self
        _, want_full = knn_oracle
        # drop the self column from the oracle (vectorized)
        rows = np.arange(len(dataset))[:, None]
        not_self = want_full != rows
        order = np.argsort(~not_self, axis=1, kind="stable")[:, :k]
        want = np.take_along_axis(want_full, order, axis=1)
        r = calc_recall(graph, want)
        assert r >= 0.85, f"nn_descent graph recall {r}"


class TestCagra:
    def test_structure(self, built_index, dataset):
        assert built_index.size == len(dataset)
        assert built_index.graph_degree == 32
        g = np.asarray(built_index.graph)
        assert g.min() >= 0 and g.max() < len(dataset)
        assert (g != np.arange(len(dataset))[:, None]).all()  # no self loops

    @pytest.mark.parametrize("itopk,min_recall", [(64, 0.90), (128, 0.95)])
    def test_recall(self, built_index, dataset, queries, itopk, min_recall):
        _, idx = cagra.search(built_index, queries, k=10,
                              params=cagra.SearchParams(itopk_size=itopk))
        _, want = naive_knn(dataset, queries, 10)
        r = calc_recall(np.asarray(idx), want)
        assert r >= min_recall, f"recall {r} < {min_recall} at itopk={itopk}"

    def test_distances_match_l2(self, built_index, dataset, queries):
        dist, idx = cagra.search(built_index, queries, k=5,
                                 params=cagra.SearchParams(itopk_size=64))
        d, i = np.asarray(dist), np.asarray(idx)
        for row in range(0, 100, 13):
            true = ((queries[row] - dataset[i[row, 0]]) ** 2).sum()
            assert abs(d[row, 0] - true) < 1e-1

    def test_search_width(self, built_index, dataset, queries):
        _, idx = cagra.search(built_index, queries, k=10,
                              params=cagra.SearchParams(itopk_size=64,
                                                        search_width=4))
        _, want = naive_knn(dataset, queries, 10)
        assert calc_recall(np.asarray(idx), want) >= 0.85

    @pytest.mark.slow
    def test_nn_descent_build(self, dataset, queries):
        index = cagra.build(dataset, cagra.IndexParams(
            intermediate_graph_degree=64, graph_degree=32,
            build_algo=cagra.BuildAlgo.NN_DESCENT, seed=0))
        _, idx = cagra.search(index, queries, k=10,
                              params=cagra.SearchParams(itopk_size=64))
        _, want = naive_knn(dataset, queries, 10)
        assert calc_recall(np.asarray(idx), want) >= 0.85

    def test_filter(self, built_index, dataset, queries):
        _, base = naive_knn(dataset, queries, 1)
        mask = np.ones(len(dataset), bool)
        mask[base[:, 0]] = False
        filt = Bitset.from_mask(jnp.asarray(mask))
        _, idx = cagra.search(built_index, queries, k=10,
                              params=cagra.SearchParams(itopk_size=64),
                              filter=filt)
        got = np.asarray(idx)
        assert all(base[i, 0] not in got[i] for i in range(len(got)))

    def test_save_load(self, tmp_path, built_index, queries):
        cagra.save(built_index, tmp_path / "cagra.raft")
        loaded = cagra.load(tmp_path / "cagra.raft")
        _, i1 = cagra.search(built_index, queries, k=5,
                             params=cagra.SearchParams(itopk_size=64))
        _, i2 = cagra.search(loaded, queries, k=5,
                             params=cagra.SearchParams(itopk_size=64))
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))

    def test_optimize_prunes_to_degree(self, dataset):
        knn = cagra.build_knn_graph(dataset[:2000], 32, seed=0)
        graph = cagra.optimize(knn, 16)
        assert graph.shape == (2000, 16)
        assert (graph != np.arange(2000)[:, None]).all()

    def test_rev_group_host_matches_jit(self):
        """The host fallback (scale guard for the monolithic device sort)
        must reproduce _rev_group_jit bit-for-bit."""
        rng = np.random.default_rng(7)
        n, keep_fwd, cap = 500, 8, 16
        pruned = rng.integers(-1, n, size=(n, 16)).astype(np.int32)
        want = np.asarray(cagra._rev_group_jit(
            jnp.asarray(pruned), keep_fwd, cap))
        got = cagra._rev_group_host(pruned, keep_fwd, cap)
        np.testing.assert_array_equal(got, want)

    def test_knn_graph_brute_exact(self, dataset, knn_oracle):
        """The brute path must produce the exact kNN graph."""
        sub = dataset[:2000]
        g = cagra.build_knn_graph(sub, 8, algo="brute")
        _, want_full = naive_knn(sub, sub, 9)
        rows = np.arange(2000)[:, None]
        not_self = want_full != rows
        order = np.argsort(~not_self, axis=1, kind="stable")[:, :8]
        want = np.take_along_axis(want_full, order, axis=1)
        assert calc_recall(g, want) >= 0.999

    def test_knn_graph_brute_parted_matches_single(self, dataset,
                                                   monkeypatch):
        """Past the compile cap the brute path splits into equal parts
        with masked padding and exact merge: same graph as one part."""
        sub = dataset[:1500]
        want = cagra.build_knn_graph(sub, 8, algo="brute")
        monkeypatch.setenv("RAFT_TPU_CAGRA_BRUTE_PART_N", "600")
        got = cagra.build_knn_graph(sub, 8, algo="brute")
        # per-row SET near-equality: part-shaped GEMMs reduce in a
        # different order, so near-tied neighbors can swap rank by one
        # ULP — including across the k boundary, which changes the set
        # for that row
        assert calc_recall(got, want) >= 0.999

    def test_knn_graph_ivf_pq_path(self, dataset):
        """The reference's ivf_pq+refine path stays available above the
        brute cutover (forced here via algo=). 1200 rows: the path cost
        is compile-dominated, so the corpus only needs to clear the
        n_lists floor — the r8 graph-build suite added ~14s of tier-1
        and this rung gave ~5s of it back."""
        g = cagra.build_knn_graph(dataset[:1200], 8, algo="ivf_pq")
        assert g.shape == (1200, 8)
        assert (g != np.arange(1200)[:, None]).all()

    def test_candidate_dtype_int8(self, built_index, dataset, queries):
        _, idx = cagra.search(built_index, queries, k=10,
                              params=cagra.SearchParams(
                                  itopk_size=64, candidate_dtype="int8"))
        _, want = naive_knn(dataset, queries, 10)
        assert calc_recall(np.asarray(idx), want) >= 0.85

    def test_seed_nodes_help_capped_traversal(self, built_index, dataset,
                                              queries):
        """The shared covering seed set (IndexParams.seed_nodes) must not
        hurt, and under a tight hop cap should beat random-only seeding
        (it starts the walk near every cluster)."""
        assert built_index.seed_nodes is not None
        unseeded = cagra.Index(built_index.dataset, built_index.graph,
                               built_index.metric, None)
        _, want = naive_knn(dataset, queries, 10)
        sp = cagra.SearchParams(itopk_size=32, search_width=4,
                                max_iterations=4)
        _, i_seed = cagra.search(built_index, queries, k=10, params=sp)
        _, i_rand = cagra.search(unseeded, queries, k=10, params=sp)
        r_seed = calc_recall(np.asarray(i_seed), want)
        r_rand = calc_recall(np.asarray(i_rand), want)
        # unclustered gaussian corpus at 4 hops: measured 0.77 vs 0.71
        # (clustered corpora show a larger gap — 0.90 vs 0.80)
        assert r_seed >= 0.7, r_seed
        assert r_seed >= r_rand - 0.02, (r_seed, r_rand)

    def test_index_as_jit_argument(self, built_index, dataset, queries):
        """The pytree carries the traversal caches and seed set
        byte-identical, so jitted functions can take the index as an
        ARGUMENT (baked closure constants exceed remote-compile limits
        at memory scale)."""
        import jax

        cagra.prepare_search(built_index)
        leaves, td = jax.tree_util.tree_flatten(built_index)
        rebuilt = jax.tree_util.tree_unflatten(td, leaves)
        np.testing.assert_array_equal(np.asarray(built_index._score_bf16),
                                      np.asarray(rebuilt._score_bf16))
        np.testing.assert_array_equal(np.asarray(built_index.seed_nodes),
                                      np.asarray(rebuilt.seed_nodes))
        fn = jax.jit(lambda q, idx: cagra.search(
            idx, q, 10, cagra.SearchParams(itopk_size=64)))
        _, i1 = fn(queries, rebuilt)
        _, i2 = cagra.search(built_index, queries, k=10,
                             params=cagra.SearchParams(itopk_size=64))
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))

    def test_max_iterations_cap(self, built_index, dataset, queries):
        """A capped traversal still reaches usable recall (the bench's
        QPS@0.95 operating point) and never exceeds the cap's work."""
        _, idx = cagra.search(built_index, queries, k=10,
                              params=cagra.SearchParams(
                                  itopk_size=32, search_width=4,
                                  max_iterations=10))
        _, want = naive_knn(dataset, queries, 10)
        assert calc_recall(np.asarray(idx), want) >= 0.80
