"""One-trace sharded dispatch pins (docs/perf.md "Sharded dispatch").

The r05 roofline blames the sharded dispatch floor on every search
rebuilding+re-tracing its whole ``shard_map`` closure; the fix routes
every sharded family and the fleet hot path through a per-index
compiled-program cache (``parallel/dispatch_cache``). This module pins
the contract end to end:

* steady-state after warmup is ZERO XLA programs per call for all 3
  sharded ANN families, the sharded kNN, and the fleet flat/pq paths
  on the virtual 2x2 mesh — healthy, through a host loss (widen rung),
  and through a ``FleetTierController``-style tier step (extending the
  PR-19 ``<= pre-step`` drill to ``== 0``);
* results are BITWISE-equal to ``RAFT_TPU_SHARDED_DISPATCH=uncached``
  per-call dispatch, dead-shard sentinel rows included;
* warmup-sweep compiles stay exempt from ``serve.recompiles`` while an
  un-warmed serving dispatch lands there under its ``sharded.<family>``
  label;
* the ``hotpath-shardmap-rebuild`` lint catches the bug class at the
  source level (fixture + whole-tree clean).
"""
import os

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from raft_tpu.neighbors import ivf_flat, ivf_pq
from raft_tpu.parallel import dispatch_cache, sharded_ann, sharded_knn
from raft_tpu.parallel.fleet import Fleet
from raft_tpu.serve import warmup as wu

pytestmark = pytest.mark.multichip

K = 5


@pytest.fixture(scope="module")
def mesh(multichip_mesh):
    return Mesh(np.array(jax.devices()[:4]), ("shard",))


@pytest.fixture(scope="module")
def dataset():
    rng = np.random.default_rng(7)
    return rng.standard_normal((8_000, 32)).astype(np.float32)


@pytest.fixture(scope="module")
def queries():
    rng = np.random.default_rng(8)
    return rng.standard_normal((8, 32)).astype(np.float32)


# module-scoped builds: the 870s tier-1 wall is tight and searches
# never mutate an index (the dispatch cache rides on it, additively)
@pytest.fixture(scope="module")
def flat_index(mesh, dataset):
    return sharded_ann.build_ivf_flat(
        dataset, mesh, ivf_flat.IndexParams(n_lists=16, seed=0))


@pytest.fixture(scope="module")
def pq_index(mesh, dataset):
    return sharded_ann.build_ivf_pq(
        dataset, mesh, ivf_pq.IndexParams(n_lists=16, pq_dim=8, seed=0))


def _steady(search, *args):
    """Prime once (pays any first-bucket trace), then count a repeat."""
    jax.block_until_ready(search(*args))
    with wu.count_compilations() as c:
        out = search(*args)
        jax.block_until_ready(out)
    return c.count, out


def _uncached(monkeypatch, search, *args):
    monkeypatch.setenv("RAFT_TPU_SHARDED_DISPATCH", "uncached")
    try:
        out = search(*args)
        jax.block_until_ready(out)
    finally:
        monkeypatch.delenv("RAFT_TPU_SHARDED_DISPATCH")
    return out


def _assert_bitwise(a, b):
    for x, y in zip(a, b):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


class TestSteadyStateZero:
    """After one call per shape bucket, repeat dispatches compile
    NOTHING — the cached jit wrapper's C++ fast path."""

    def test_ivf_flat(self, flat_index, queries, monkeypatch):
        s = sharded_ann.make_searcher(flat_index)
        n, out = _steady(s, queries, K)
        assert n == 0
        _assert_bitwise(out, _uncached(monkeypatch, s, queries, K))
        assert dispatch_cache.stats(flat_index)["programs"] >= 1

    def test_ivf_pq(self, pq_index, queries, monkeypatch):
        s = sharded_ann.make_searcher(pq_index)
        n, out = _steady(s, queries, K)
        assert n == 0
        _assert_bitwise(out, _uncached(monkeypatch, s, queries, K))

    def test_cagra(self, mesh, dataset, queries):
        from raft_tpu.neighbors import cagra

        small = dataset[:2_000]
        idx = sharded_ann.build_cagra(
            small, mesh, cagra.IndexParams(graph_degree=16))
        s = sharded_ann.make_searcher(idx)
        n, _ = _steady(s, queries, K)
        assert n == 0

    def test_sharded_knn(self, mesh, dataset, queries, monkeypatch):
        idx = sharded_knn.build(dataset, mesh)
        search = lambda q, k: sharded_knn.search(idx, q, k)
        n, out = _steady(search, queries, K)
        assert n == 0
        _assert_bitwise(out, _uncached(monkeypatch, search, queries, K))

    def test_query_count_rides_one_python_key(self, flat_index, queries):
        """m is shape-keyed by jit, not baked into the Python key: two
        batch sizes share one cache entry (two executables inside)."""
        s = sharded_ann.make_searcher(flat_index)
        before = dispatch_cache.stats(flat_index)["programs"]
        jax.block_until_ready(s(queries, K))
        jax.block_until_ready(s(queries[:4], K))
        assert dispatch_cache.stats(flat_index)["programs"] == max(
            before, 1)

    def test_dead_shard_reuses_program_and_sentinels_bitwise(
            self, flat_index, queries, monkeypatch):
        """The health mask is a TRACED argument: killing a shard must
        not re-trace, and the sentinel rows (+inf, -1) must be bitwise
        identical to uncached dispatch."""
        s = sharded_ann.make_searcher(flat_index, allow_partial=True)
        jax.block_until_ready(s(queries, K))
        flat_index.mark_shard_failed(2)
        try:
            with wu.count_compilations() as c:
                out = s(queries, K)
                jax.block_until_ready(out)
            assert c.count == 0
            assert not bool(np.asarray(out[2], bool)[2])
            _assert_bitwise(out, _uncached(monkeypatch, s, queries, K))
        finally:
            flat_index.mark_shard_failed(2, ok=True)


class TestWarmupSharded:
    def test_warmup_precompiles_then_zero(self, flat_index, queries):
        """A fresh (m, k) bucket warmed via warmup_sharded serves its
        FIRST real request with zero compiles."""
        n = wu.warmup_sharded(flat_index, k_buckets=[7], m_buckets=[16])
        assert n > 0                 # (16, 7) was never traced before
        s = sharded_ann.make_searcher(flat_index)
        q16 = np.concatenate([queries, queries])
        with wu.count_compilations() as c:
            jax.block_until_ready(s(q16, 7))
        assert c.count == 0

    def test_warmup_exempt_unwarmed_dispatch_labeled(self, flat_index,
                                                     queries):
        """Warmup compiles never land in serve.recompiles; an un-warmed
        SERVING dispatch does, under its sharded.<family> site label."""
        from raft_tpu.core import events
        from raft_tpu.serve import metrics

        wu.install_recompile_watch()
        before = metrics.counter("serve.recompiles").value
        n = wu.warmup_sharded(flat_index, k_buckets=[6], m_buckets=[16])
        assert n > 0
        assert metrics.counter("serve.recompiles").value == before
        # cold serving bucket: label must reach the watch + the ring
        s = sharded_ann.make_searcher(flat_index)
        jax.block_until_ready(s(queries, 9))
        assert metrics.counter("serve.recompiles").value > before
        assert any(e["site"].startswith("sharded.ivf_flat:8x9")
                   for e in events.recent(kind="xla_compile"))

    def test_widen_rungs_cover_auto_widen(self, flat_index):
        """The warmed ladder contains every effective n_probes the
        degradation auto-widen can produce (identity at full health)."""
        rungs = sharded_ann.widen_rungs(flat_index, 4)
        assert 4 in rungs
        assert all(4 <= r <= 16 for r in rungs)
        engs = sharded_ann.warmup_searchers(
            flat_index, ivf_flat.SearchParams(n_probes=4))
        assert "base" in engs and len(engs) >= len(rungs)


class TestFleetDispatch:
    """Fleet hot path on the virtual 2x2 mesh: hierarchical merge,
    budgeted cold tier, host loss, tier step — all on cached buckets."""

    @pytest.fixture(scope="class")
    def fleet(self):
        return Fleet.virtual(2, 2)

    @pytest.fixture(scope="class")
    def fleet_pq(self, fleet, dataset, queries):
        # budget sized so level 0 already has cold lists: the warmup
        # sweep then covers the cold-merge path too
        idx = fleet.build_ivf_pq(
            dataset, ivf_pq.IndexParams(n_lists=16, pq_dim=8, seed=0),
            hbm_budget_gb=30e3 / (1 << 30))
        assert any(t.n_cold_lists for t in idx._fleet_tiers.values())
        sp = ivf_pq.SearchParams(n_probes=4)
        wu.warmup_sharded(idx, k_buckets=[K], m_buckets=[8],
                          params=sp, fleet=fleet)
        return idx, sp

    def test_warmed_fleet_first_search_zero(self, fleet, fleet_pq,
                                            queries):
        idx, sp = fleet_pq
        with wu.count_compilations() as c:
            out = fleet.search(idx, queries, K, params=sp)
            jax.block_until_ready(out)
        assert c.count == 0

    def test_host_loss_widen_zero_and_bitwise(self, fleet, fleet_pq,
                                              queries, monkeypatch):
        """mark_host_failed -> auto-widened n_probes lands on the
        warmed rung: zero compiles, bitwise vs uncached (hier merge +
        dead-host sentinel path included)."""
        idx, sp = fleet_pq
        jax.block_until_ready(fleet.search(idx, queries, K, params=sp))
        fleet.mark_host_failed(1)
        try:
            with wu.count_compilations() as c:
                out = fleet.search(idx, queries, K, params=sp)
                jax.block_until_ready(out)
            assert c.count == 0
            ref = _uncached(
                monkeypatch,
                lambda q, k: fleet.search(idx, q, k, params=sp),
                queries, K)
            _assert_bitwise(out[:2], ref[:2])
        finally:
            fleet.mark_host_failed(1, ok=True)

    def test_tier_step_zero_compiles(self, fleet, fleet_pq, queries):
        """PR-19 drill pinned post-step compiles <= pre-step; the
        pinned chunk geometry + cached resident programs make it 0."""
        idx, sp = fleet_pq
        jax.block_until_ready(fleet.search(idx, queries, K, params=sp))
        ctx = idx._fleet_ctx
        level0 = ctx["levels"][0]
        fleet._apply_tier_level(idx, 0, level0 + 1, level0, "drill")
        try:
            with wu.count_compilations() as c:
                out = fleet.search(idx, queries, K, params=sp)
                jax.block_until_ready(out)
            assert c.count == 0
        finally:
            fleet._apply_tier_level(idx, 0, level0, level0 + 1,
                                    "headroom")

    def test_fleet_flat_rung_zero(self, fleet, dataset, queries):
        """The int8 flat rung (family=ivf_flat) warms and serves on
        cached buckets too."""
        idx = fleet.build_ivf_pq(
            dataset, ivf_pq.IndexParams(n_lists=16, seed=0),
            store_dtype="int8")
        assert idx.family == "ivf_flat"
        sp = ivf_flat.SearchParams(n_probes=4)
        wu.warmup_sharded(idx, k_buckets=[K], m_buckets=[8],
                          params=sp, fleet=fleet)
        with wu.count_compilations() as c:
            jax.block_until_ready(fleet.search(idx, queries, K, params=sp))
        assert c.count == 0


class TestShardmapLint:
    """hotpath-shardmap-rebuild: per-call shard_map construction on a
    serving path is machine-checked."""

    def test_violation_fires(self):
        from raft_tpu.analysis import hotpath_audit

        src = (
            "from raft_tpu.utils import shard_map_compat\n"
            "def search(index, q, k):\n"
            "    fn = shard_map_compat(lambda x: x, mesh=index.mesh)\n"
            "    return fn(q)\n")
        fs = hotpath_audit.shardmap_lint_source(src, "fixture.py")
        assert [f.rule for f in fs] == ["hotpath-shardmap-rebuild"]
        assert fs[0].symbol == "search:shard_map_compat"
        assert fs[0].line == 3

    def test_cache_miss_branch_clean(self):
        """The dispatch_cache idiom — construction under an
        ``if fn is None:`` miss check — is the sanctioned pattern."""
        from raft_tpu.analysis import hotpath_audit

        src = (
            "from raft_tpu.utils import shard_map_compat\n"
            "def search(index, q, cache, key):\n"
            "    fn = cache.get(key)\n"
            "    if fn is None:\n"
            "        fn = shard_map_compat(lambda x: x, mesh=index.mesh)\n"
            "        cache[key] = fn\n"
            "    return fn(q)\n")
        assert hotpath_audit.shardmap_lint_source(src, "fixture.py") == []

    def test_offpath_helpers_clean(self):
        from raft_tpu.analysis import hotpath_audit

        src = (
            "from raft_tpu.utils import shard_map_compat\n"
            "def warmup_programs(index):\n"
            "    return shard_map_compat(lambda x: x, mesh=index.mesh)\n"
            "def build_index(data):\n"
            "    return shard_map_compat(lambda x: x, mesh=None)\n")
        assert hotpath_audit.shardmap_lint_source(src, "fixture.py") == []

    def test_whole_tree_clean(self):
        from raft_tpu import analysis
        from raft_tpu.analysis import hotpath_audit

        fs = hotpath_audit.shardmap_lint(analysis.repo_root())
        assert fs == [], [f.render() for f in fs]

    def test_rule_registered(self):
        from raft_tpu import analysis

        assert "hotpath-shardmap-rebuild" in analysis.KNOWN_RULES
        assert "hotpath-shardmap-rebuild" in analysis.PASS_RULES["hotpath"]
