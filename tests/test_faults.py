"""Resilient-execution-layer tests: fault injection, guarded kernel
fallback, deadlines, degraded sharded search, durable index I/O.

Everything here is deterministic and CPU-safe (the ``faults`` marker).
The acceptance bar: with injection forcing kernel failure at every gated
site, search results are BIT-IDENTICAL to the fallback engine run
directly; a dead shard yields a degraded merged answer with the loss
reported; corrupt/truncated index files raise a typed error naming the
bad section; interrupted saves never leave a partial file.

Index builds dominate this file's runtime on the 1-core CI box, so every
index is a module-scoped fixture shared across test classes.
"""
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from raft_tpu.core import faults
from raft_tpu.core.deadline import Deadline, DeadlineExceeded
from raft_tpu.core.errors import CorruptIndexError, ShardsDownError
from raft_tpu.core.resources import Resources

pytestmark = pytest.mark.faults


@pytest.fixture(autouse=True)
def _no_disk_autotune(monkeypatch):
    # guard demotions ride the autotune cache; tests must not touch the
    # user-level JSON
    monkeypatch.setenv("RAFT_TPU_AUTOTUNE_CACHE", "")


@pytest.fixture(scope="module")
def corpus():
    rng = np.random.default_rng(42)
    data = rng.standard_normal((800, 16)).astype(np.float32)
    q = rng.standard_normal((24, 16)).astype(np.float32)
    return data, q


@pytest.fixture(scope="module")
def flat_index(corpus):
    from raft_tpu.neighbors import ivf_flat

    return ivf_flat.build(corpus[0], ivf_flat.IndexParams(n_lists=8, seed=0))


@pytest.fixture(scope="module")
def pq_index(corpus):
    from raft_tpu.neighbors import ivf_pq

    return ivf_pq.build(corpus[0], ivf_pq.IndexParams(
        n_lists=8, pq_dim=4, pq_bits=4, seed=0))


@pytest.fixture(scope="module")
def bf_index(corpus):
    from raft_tpu.neighbors import brute_force

    return brute_force.build(corpus[0])


@pytest.fixture(scope="module")
def cagra_index(corpus):
    from raft_tpu.neighbors import cagra

    return cagra.build(corpus[0], cagra.IndexParams(
        graph_degree=8, intermediate_graph_degree=12, seed=0))


def _ticking(ticks):
    it = iter(ticks)
    return lambda: next(it)


class TestFaultFramework:
    def test_spec_parse(self):
        f = faults._parse_spec("kernel_compile@ivf_flat.*:3=0.5")
        assert f.kind == "kernel_compile" and f.pattern == "ivf_flat.*"
        assert f.count == 3 and f.value == "0.5"
        f = faults._parse_spec("shard_dead")
        assert f.pattern == "*" and f.count is None and f.value is None

    def test_inject_scoped_and_counted(self):
        # a private kind: this test must hold even under the faults lane
        # (RAFT_TPU_FAULTS='kernel_compile@*' arming everything ambient)
        assert faults.fired("unit_kind", "x.y") is None
        with faults.inject("unit_kind", "x.*", count=2):
            assert faults.fired("unit_kind", "x.y") is not None
            assert faults.fired("unit_kind", "nomatch") is None
            assert faults.fired("unit_kind", "x.z") is not None
            assert faults.fired("unit_kind", "x.y") is None   # spent
        assert faults.fired("unit_kind", "x.y") is None       # scoped

    def test_check_raises(self):
        with faults.inject("io_error", "site.a"):
            with pytest.raises(faults.InjectedFault, match="site.a"):
                faults.check("io_error", "site.a")
        faults.check("io_error", "site.a")  # disarmed: no raise

    def test_env_spec(self):
        os.environ["RAFT_TPU_FAULTS"] = "slow_dispatch@env.site:1=0"
        try:
            faults.reload_env()
            assert faults.fired("slow_dispatch", "env.site") is not None
            assert faults.fired("slow_dispatch", "env.site") is None
        finally:
            os.environ.pop("RAFT_TPU_FAULTS", None)
            faults.reload_env()

    def test_corrupt_flips_one_bit(self):
        data = bytes(range(64))
        with faults.inject("corrupt_bytes", "c.site", value=10):
            out = faults.corrupt("c.site", data)
        assert out != data and len(out) == len(data)
        assert out[10] == data[10] ^ 1
        assert faults.corrupt("c.site", data) == data  # disarmed


class TestGuardedFallback:
    """Acceptance: with kernel_compile forced at every gated site, the
    searches return bit-identical results to the fallback engine run
    directly (the fallbacks are exact)."""

    def test_select_k_kpass_falls_back_exact(self):
        from raft_tpu.matrix.select_k import select_k

        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((130, 1024)), jnp.float32)
        with faults.inject("kernel_compile"):
            v1, i1 = select_k(x, 5, algo="kpass")
        v2, i2 = select_k(x, 5, algo="topk")
        np.testing.assert_array_equal(np.asarray(v1), np.asarray(v2))
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))

    def test_ivf_flat_scan_falls_back_exact(self, corpus, flat_index):
        from raft_tpu.neighbors import ivf_flat

        _, q = corpus
        sp = ivf_flat.SearchParams(n_probes=8)
        with faults.inject("kernel_compile"):
            dp, ip = ivf_flat.search(flat_index, q, 8, sp, algo="pallas")
        dx, ix = ivf_flat.search(flat_index, q, 8, sp, algo="xla")
        np.testing.assert_array_equal(np.asarray(ip), np.asarray(ix))
        np.testing.assert_array_equal(np.asarray(dp), np.asarray(dx))

    def test_ivf_pq_scan_falls_back_exact(self, corpus, pq_index):
        from raft_tpu.neighbors import ivf_pq

        _, q = corpus
        sp = ivf_pq.SearchParams(n_probes=8)
        with faults.inject("kernel_compile"):
            dp, ip = ivf_pq.search(pq_index, q, 8, sp, algo="pallas")
        dx, ix = ivf_pq.search(pq_index, q, 8, sp, algo="xla")
        np.testing.assert_array_equal(np.asarray(ip), np.asarray(ix))
        np.testing.assert_array_equal(np.asarray(dp), np.asarray(dx))

    def test_brute_force_fused_falls_back_exact(self, corpus, bf_index):
        from raft_tpu.neighbors import brute_force

        _, q = corpus
        with faults.inject("kernel_compile"):
            dp, ip = brute_force.search(bf_index, q, 10, algo="pallas")
        dm, im = brute_force.search(bf_index, q, 10, algo="matmul")
        np.testing.assert_array_equal(np.asarray(ip), np.asarray(im))
        np.testing.assert_array_equal(np.asarray(dp), np.asarray(dm))

    def test_cagra_unaffected_by_kernel_faults(self, corpus, cagra_index):
        # cagra's only kernel dependency is select_k's (guarded) KPASS
        # engine; forcing kernel failure everywhere must not change its
        # results
        from raft_tpu.neighbors import cagra

        _, q = corpus
        d0, i0 = cagra.search(cagra_index, q, 5)
        with faults.inject("kernel_compile"):
            d1, i1 = cagra.search(cagra_index, q, 5)
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
        np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))

    def test_real_failure_demotes_and_logs_once(self):
        from raft_tpu.ops import autotune, guarded

        calls = []

        def boom():
            calls.append(1)
            raise RuntimeError("mosaic lowering died")

        try:
            assert guarded.guarded_call("t.site", boom, lambda: "fb") == "fb"
            # demoted: the second call must not touch the kernel path
            assert guarded.guarded_call("t.site", boom, lambda: "fb") == "fb"
            assert len(calls) == 1
            assert "t.site" in guarded.demoted_sites()
            # the demotion is recorded in the autotune cache
            assert autotune.lookup(guarded._guard_key("t.site")) == "fallback"
        finally:
            guarded.reset()
        assert "t.site" not in guarded.demoted_sites()
        assert autotune.lookup(guarded._guard_key("t.site")) is None

    def test_ephemeral_demotion_never_hits_disk(self, tmp_path, monkeypatch):
        """A persist=False guard demotion must not leak into the disk
        cache when a later ordinary record() dumps it."""
        import json

        from raft_tpu.ops import autotune

        cache = tmp_path / "autotune.json"
        monkeypatch.setenv("RAFT_TPU_AUTOTUNE_CACHE", str(cache))
        try:
            autotune.record("guard:test:x", "fallback", persist=False)
            autotune.record("select_k_test_key", "topk")   # triggers save
            disk = json.loads(cache.read_text())
            assert "select_k_test_key" in disk
            assert "guard:test:x" not in disk
            # still honored in-process
            assert autotune.lookup("guard:test:x") == "fallback"
        finally:
            autotune.forget("guard:test:x")
            autotune.forget("select_k_test_key")

    def test_injected_faults_do_not_demote(self):
        from raft_tpu.ops import guarded

        ran = []
        with faults.inject("kernel_compile", "i.site", count=1):
            assert guarded.guarded_call(
                "i.site", lambda: "kern", lambda: "fb") == "fb"
        # injection spent: the kernel path runs again (no sticky demotion)
        assert guarded.guarded_call(
            "i.site", lambda: ran.append(1) or "kern", lambda: "fb") == "kern"
        assert ran and "i.site" not in guarded.demoted_sites()

    def test_cancellation_passes_through(self):
        from raft_tpu.core.interruptible import InterruptedException
        from raft_tpu.ops import guarded

        def cancelled():
            raise InterruptedException("stop")

        with pytest.raises(InterruptedException):
            guarded.guarded_call("c.site", cancelled, lambda: "fb")
        assert "c.site" not in guarded.demoted_sites()


class TestDeadline:
    def test_deadline_clock(self):
        dl = Deadline(1.0, clock=_ticking([0.0, 0.5, 1.5]))
        assert not dl.expired()
        assert dl.expired()

    def test_checkpoint_attaches_partial(self):
        res = Resources(deadline=Deadline(
            1.0, clock=_ticking([0.0, 2.0, 2.0])))
        from raft_tpu.core import deadline as dl_mod

        with pytest.raises(DeadlineExceeded) as ei:
            dl_mod.checkpoint(res, partial=lambda: "the-partial")
        assert ei.value.partial == "the-partial"

    def test_ivf_flat_partial_results(self, corpus, flat_index):
        """A deadline shorter than the chunked search raises BETWEEN
        chunks with the completed chunks' results attached."""
        from raft_tpu.neighbors import ivf_flat

        _, q = corpus
        sp = ivf_flat.SearchParams(n_probes=8)
        dx, ix = ivf_flat.search(flat_index, q, 8, sp, algo="xla")
        # ticks: Deadline init, ck@chunk0 (ok), ck@chunk1 (expired) + the
        # elapsed() read in the error message
        res = Resources(deadline=Deadline(
            1.0, clock=_ticking([0.0, 0.5, 2.0, 2.0])))
        with pytest.raises(DeadlineExceeded) as ei:
            ivf_flat.search(flat_index, q, 8, sp, algo="xla", query_chunk=8,
                            res=res)
        pd, pi = ei.value.partial
        assert pd.shape == (8, 8)
        np.testing.assert_array_equal(np.asarray(pi), np.asarray(ix[:8]))
        np.testing.assert_array_equal(np.asarray(pd), np.asarray(dx[:8]))

    def test_ivf_pq_partial_results(self, corpus, pq_index):
        from raft_tpu.neighbors import ivf_pq

        _, q = corpus
        sp = ivf_pq.SearchParams(n_probes=8)
        _, ix = ivf_pq.search(pq_index, q, 8, sp, algo="xla")
        res = Resources(deadline=Deadline(
            1.0, clock=_ticking([0.0, 0.5, 2.0, 2.0])))
        with pytest.raises(DeadlineExceeded) as ei:
            ivf_pq.search(pq_index, q, 8, sp, algo="xla", query_chunk=8,
                          res=res)
        pd, pi = ei.value.partial
        assert pd.shape == (8, 8)
        np.testing.assert_array_equal(np.asarray(pi), np.asarray(ix[:8]))

    def test_brute_force_partial_results(self, corpus, bf_index):
        from raft_tpu.neighbors import brute_force

        _, q = corpus
        _, ix = brute_force.search(bf_index, q, 5)
        res = Resources(deadline=Deadline(
            1.0, clock=_ticking([0.0, 0.5, 2.0, 2.0])))
        with pytest.raises(DeadlineExceeded) as ei:
            brute_force.search(bf_index, q, 5, res=res, query_chunk=8)
        pd, pi = ei.value.partial
        assert pd.shape == (8, 5)
        np.testing.assert_array_equal(np.asarray(pi), np.asarray(ix[:8]))

    def test_cagra_deadline_between_chunks(self, corpus, cagra_index):
        from raft_tpu.neighbors import cagra

        _, q = corpus
        _, ix = cagra.search(cagra_index, q, 5)
        res = Resources(deadline=Deadline(
            1.0, clock=_ticking([0.0, 0.5, 2.0, 2.0])))
        with pytest.raises(DeadlineExceeded) as ei:
            cagra.search(cagra_index, q, 5, res=res, query_chunk=8)
        pd, pi = ei.value.partial
        assert pd.shape == (8, 5)
        np.testing.assert_array_equal(np.asarray(pi), np.asarray(ix[:8]))

    def test_bare_deadline_as_res(self, corpus, bf_index):
        """A bare Deadline passed as res is honored, not a silent no-op
        — even when the whole batch fits one chunk (pre-dispatch check)."""
        from raft_tpu.neighbors import brute_force

        _, q = corpus
        with pytest.raises(DeadlineExceeded):
            brute_force.search(bf_index, q, 5,
                               res=Deadline(1.0,
                                            clock=_ticking([0.0, 5.0, 5.0])))

    def test_expired_before_first_chunk_has_empty_partial(self, corpus,
                                                          flat_index):
        from raft_tpu.neighbors import ivf_flat

        _, q = corpus
        res = Resources(deadline=Deadline(
            1.0, clock=_ticking([0.0, 5.0, 5.0])))
        with pytest.raises(DeadlineExceeded) as ei:
            ivf_flat.search(flat_index, q, 8, ivf_flat.SearchParams(n_probes=8),
                            algo="xla", query_chunk=8, res=res)
        assert ei.value.partial is None

    def test_interruptible_token_protocol(self, corpus, flat_index):
        """checkpoint is a full cancellation point: a cancelled token
        aborts the chunked search through the same probe."""
        from raft_tpu.core import interruptible
        from raft_tpu.neighbors import ivf_flat

        _, q = corpus
        sp = ivf_flat.SearchParams(n_probes=8)
        interruptible.cancel()
        with pytest.raises(interruptible.InterruptedException):
            ivf_flat.search(flat_index, q, 8, sp, algo="xla", query_chunk=8)
        # token resets after raising (interruptible contract)
        ivf_flat.search(flat_index, q, 8, sp, algo="xla", query_chunk=8)


@pytest.fixture(scope="module")
def mesh():
    return Mesh(np.array(jax.devices()[:4]), ("shard",))


@pytest.fixture(scope="module")
def sharded_data():
    rng = np.random.default_rng(17)
    data = rng.standard_normal((1200, 16)).astype(np.float32)
    q = rng.standard_normal((20, 16)).astype(np.float32)
    return data, q


@pytest.fixture(scope="module")
def sharded_flat(mesh, sharded_data):
    from raft_tpu.neighbors import ivf_flat
    from raft_tpu.parallel import sharded_ann

    return sharded_ann.build_ivf_flat(
        sharded_data[0], mesh, ivf_flat.IndexParams(n_lists=8, seed=0))


class TestDegradedSharded:
    """Acceptance: a forced single-shard failure with allow_partial=True
    returns merged results from the surviving shards, with shards_ok
    reporting the loss; without allow_partial it raises.

    Shard i of the 4-shard mesh owns global rows [i*300, (i+1)*300)."""

    def test_ivf_flat_degraded(self, sharded_flat, sharded_data):
        from raft_tpu.neighbors import ivf_flat
        from raft_tpu.parallel import sharded_ann

        data, q = sharded_data
        sp = ivf_flat.SearchParams(n_probes=8)
        with faults.inject("shard_dead", "sharded_ann.ivf_flat.shard1"):
            with pytest.raises(ShardsDownError, match=r"\[1\]"):
                sharded_ann.search_ivf_flat(sharded_flat, q, 5, sp)
        with faults.inject("shard_dead", "sharded_ann.ivf_flat.shard1"):
            d, i, ok = sharded_ann.search_ivf_flat(
                sharded_flat, q, 5, sp, allow_partial=True)
        assert list(ok) == [True, False, True, True]
        got = np.asarray(i)
        # shard 1 owns global rows [300, 600): none may appear
        assert not (((got >= 300) & (got < 600)).any())
        # survivors still produce a full merged answer
        assert (got >= 0).all() and np.isfinite(np.asarray(d)).all()
        # degraded result == exact search over the surviving rows
        from ann_utils import calc_recall, naive_knn

        keep = np.concatenate([np.arange(0, 300), np.arange(600, 1200)])
        _, want = naive_knn(data[keep], q, 5)
        assert calc_recall(got, keep[want]) == 1.0

    # tier-1 wall: sticky mark/re-arm + healthy-API semantics are now
    # asserted (against BOTH merge engines) by the consolidated
    # test_ring_topk.py acceptance flow; the standalone form is slow-lane
    @pytest.mark.slow
    def test_sticky_flag_and_healthy_api(self, sharded_flat, sharded_data):
        from raft_tpu.neighbors import ivf_flat
        from raft_tpu.parallel import sharded_ann

        _, q = sharded_data
        sp = ivf_flat.SearchParams(n_probes=8)
        sharded_flat.mark_shard_failed(2)
        try:
            _, i, ok = sharded_ann.search_ivf_flat(sharded_flat, q, 5, sp,
                                                   allow_partial=True)
            assert list(ok) == [True, True, False, True]
            got = np.asarray(i)
            assert not (((got >= 600) & (got < 900)).any())
        finally:
            sharded_flat.mark_shard_failed(2, ok=True)   # re-arm
        # healthy index: legacy 2-tuple API, allow_partial reports all-ok
        out = sharded_ann.search_ivf_flat(sharded_flat, q, 5, sp)
        assert len(out) == 2
        d, i, ok = sharded_ann.search_ivf_flat(sharded_flat, q, 5, sp,
                                               allow_partial=True)
        assert ok.all()
        np.testing.assert_array_equal(np.asarray(i), np.asarray(out[1]))

    # tier-1 wall: every family's degraded merge now flows through the
    # one _merged_shard_search chokepoint (sharded_ann); ivf_flat (above,
    # fault-injected) and cagra (below) keep the tier-1 coverage and the
    # pq-specific form moves to the slow lane
    @pytest.mark.slow
    def test_ivf_pq_degraded(self, mesh, sharded_data):
        from raft_tpu.neighbors import ivf_pq
        from raft_tpu.parallel import sharded_ann

        data, q = sharded_data
        # pq_bits=4: a 300-row shard has too few training residuals for
        # the default 256-entry codebooks
        index = sharded_ann.build_ivf_pq(
            data, mesh, ivf_pq.IndexParams(n_lists=8, pq_dim=8, pq_bits=4,
                                           seed=0))
        sp = ivf_pq.SearchParams(n_probes=8)
        with faults.inject("shard_timeout", "sharded_ann.ivf_pq.shard3"):
            d, i, ok = sharded_ann.search_ivf_pq(
                index, q, 5, sp, allow_partial=True)
        assert list(ok) == [True, True, True, False]
        got = np.asarray(i)
        assert not (got >= 900).any()   # shard 3 owns [900, 1200)
        assert (got >= 0).all()

    def test_cagra_degraded(self, mesh, sharded_data):
        from raft_tpu.neighbors import cagra
        from raft_tpu.parallel import sharded_ann

        data, q = sharded_data
        index = sharded_ann.build_cagra(
            data, mesh, cagra.IndexParams(
                intermediate_graph_degree=16, graph_degree=8, seed=0))
        sp = cagra.SearchParams(itopk_size=32)
        with faults.inject("shard_dead", "sharded_ann.cagra.shard0"):
            d, i, ok = sharded_ann.search_cagra(
                index, q, 5, sp, allow_partial=True)
        assert list(ok) == [False, True, True, True]
        got = np.asarray(i)
        assert not ((got >= 0) & (got < 300)).any()
        assert (got >= 0).all()


class TestDurableIO:
    """Acceptance: truncated or bit-flipped files raise CorruptIndexError
    naming the bad section; interrupted saves never leave a partial file
    at the target path."""

    def test_corrupt_named_section(self, tmp_path, rng):
        from raft_tpu.core import serialize

        path = str(tmp_path / "x.raft")
        serialize.save_arrays(path, "t", 1, {"n": 4}, {
            "aa": rng.standard_normal((8, 4)).astype(np.float32),
            "zz": np.arange(8, dtype=np.int64)})
        raw = bytearray(open(path, "rb").read())
        raw[-3] ^= 0x10                 # inside the LAST array section
        open(path, "wb").write(bytes(raw))
        with pytest.raises(CorruptIndexError) as ei:
            serialize.load_arrays(path)
        assert ei.value.section == "zz"

    def test_truncated_named_section(self, tmp_path, rng):
        from raft_tpu.core import serialize

        path = str(tmp_path / "x.raft")
        serialize.save_arrays(path, "t", 1, {}, {
            "data": rng.standard_normal((64, 8)).astype(np.float32)})
        raw = open(path, "rb").read()
        open(path, "wb").write(raw[: len(raw) - 40])
        with pytest.raises(CorruptIndexError) as ei:
            serialize.load_arrays(path)
        assert ei.value.section == "data"

    def test_corrupt_length_prefix_is_contained(self, tmp_path, rng):
        """A flipped high bit in a length prefix must report corruption,
        not attempt an exabyte allocation."""
        from raft_tpu.core import serialize

        path = str(tmp_path / "x.raft")
        serialize.save_arrays(path, "t", 1, {}, {
            "data": rng.standard_normal((16, 4)).astype(np.float32)})
        raw = bytearray(open(path, "rb").read())
        at = raw.find(b"\x04\x00data") + 6
        raw[at + 7] ^= 0x40             # high byte of the little-endian <Q
        open(path, "wb").write(bytes(raw))
        with pytest.raises(CorruptIndexError) as ei:
            serialize.load_arrays(path)
        assert ei.value.section == "data"

    def test_legacy_files_still_load(self, rng):
        # a file in the pre-checksum layout (header + count + raw frames)
        import io
        import struct

        from raft_tpu.core import serialize

        arrays = {"a": rng.standard_normal((5, 3)).astype(np.float32)}
        meta = {"metric": "l2", "n": 5}
        buf = io.BytesIO()
        serialize.serialize_header(buf, "legacy", 2, meta)
        buf.write(struct.pack("<I", 1))
        buf.write(struct.pack("<H", 1) + b"a")
        serialize.serialize_array(buf, arrays["a"])
        buf.seek(0)
        kind, version, meta2, arrays2 = serialize.load_arrays(buf, "legacy")
        assert (kind, version, meta2) == ("legacy", 2, meta)
        np.testing.assert_array_equal(arrays2["a"], arrays["a"])

    def test_interrupted_save_is_atomic(self, tmp_path, rng):
        from raft_tpu.core import serialize

        path = str(tmp_path / "idx.raft")
        arrays = {"d": rng.standard_normal((16, 4)).astype(np.float32)}
        serialize.save_arrays(path, "t", 1, {}, arrays)
        with faults.inject("io_error", "core.serialize.save_arrays"):
            with pytest.raises(faults.InjectedFault):
                serialize.save_arrays(path, "t", 9, {"new": True}, arrays)
        # the previous good file is intact and no temp litter remains
        _, version, meta, _ = serialize.load_arrays(path)
        assert version == 1 and "new" not in meta
        assert os.listdir(tmp_path) == ["idx.raft"]

    def test_interrupted_first_save_leaves_nothing(self, tmp_path, rng):
        from raft_tpu.core import serialize

        path = str(tmp_path / "fresh.raft")
        with faults.inject("io_error", "core.serialize.save_arrays"):
            with pytest.raises(faults.InjectedFault):
                serialize.save_arrays(path, "t", 1, {}, {
                    "d": rng.standard_normal((4, 4)).astype(np.float32)})
        assert os.listdir(tmp_path) == []

    def test_ivf_flat_corrupt_index(self, tmp_path, flat_index):
        from raft_tpu.neighbors import ivf_flat

        path = tmp_path / "ivf.raft"
        ivf_flat.save(flat_index, path)
        loaded = ivf_flat.load(path)     # clean file round-trips
        assert loaded.size == flat_index.size
        raw = bytearray(open(path, "rb").read())
        raw[len(raw) // 2] ^= 0x40
        open(path, "wb").write(bytes(raw))
        with pytest.raises(CorruptIndexError) as ei:
            ivf_flat.load(path)
        assert ei.value.section

    def test_ivf_pq_corrupt_index(self, tmp_path, pq_index):
        from raft_tpu.neighbors import ivf_pq

        path = tmp_path / "pq.raft"
        ivf_pq.save(pq_index, path)
        assert ivf_pq.load(path).size == pq_index.size
        raw = bytearray(open(path, "rb").read())
        raw[len(raw) // 2] ^= 0x40
        open(path, "wb").write(bytes(raw))
        with pytest.raises(CorruptIndexError):
            ivf_pq.load(path)

    def test_cagra_corrupt_and_write_fault(self, tmp_path, cagra_index):
        from raft_tpu.neighbors import cagra

        path = tmp_path / "cagra.raft"
        # corruption injected at WRITE time (after checksumming) is
        # caught by the reader's CRC — the storage-rot model
        with faults.inject("corrupt_bytes", "core.serialize.array.graph"):
            cagra.save(cagra_index, path)
        with pytest.raises(CorruptIndexError) as ei:
            cagra.load(path)
        assert ei.value.section == "graph"
        cagra.save(cagra_index, path)
        loaded = cagra.load(path)
        np.testing.assert_array_equal(np.asarray(loaded.graph),
                                      np.asarray(cagra_index.graph))
