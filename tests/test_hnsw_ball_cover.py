"""hnsw (CPU graph search) + ball_cover / epsilon_neighborhood tests
(oracle: exact brute force, recall thresholds as in NEIGHBORS_TEST)."""
import numpy as np
import pytest

from ann_utils import calc_recall, naive_knn
from raft_tpu.neighbors import ball_cover, cagra, hnsw


@pytest.fixture(scope="module")
def dataset():
    rng = np.random.default_rng(17)
    return rng.standard_normal((4_000, 24)).astype(np.float32)


@pytest.fixture(scope="module")
def queries():
    rng = np.random.default_rng(18)
    return rng.standard_normal((60, 24)).astype(np.float32)


@pytest.fixture(scope="module")
def oracle(dataset, queries):
    return naive_knn(dataset, queries, 10)


@pytest.fixture(scope="module")
def cagra_index(dataset):
    return cagra.build(dataset, cagra.IndexParams(
        intermediate_graph_degree=48, graph_degree=24, seed=0))


class TestHnsw:
    def test_recall(self, cagra_index, queries, oracle):
        h = hnsw.from_cagra(cagra_index)
        d, i = hnsw.search(h, queries, 10, ef=96)
        _, want = oracle
        r = calc_recall(i, want)
        assert r >= 0.9, f"hnsw recall {r}"
        assert (i >= 0).all()

    def test_ef_improves_recall(self, cagra_index, queries, oracle):
        h = hnsw.from_cagra(cagra_index)
        _, want = oracle
        _, i_lo = hnsw.search(h, queries, 10, ef=16)
        _, i_hi = hnsw.search(h, queries, 10, ef=128)
        assert calc_recall(i_hi, want) >= calc_recall(i_lo, want)

    def test_save_load_roundtrip(self, cagra_index, queries, tmp_path):
        h = hnsw.from_cagra(cagra_index)
        hnsw.save(h, tmp_path / "h.bin")
        h2 = hnsw.load(tmp_path / "h.bin")
        d1, i1 = hnsw.search(h, queries[:5], 5)
        d2, i2 = hnsw.search(h2, queries[:5], 5)
        np.testing.assert_array_equal(i1, i2)

    def test_distances_are_exact(self, cagra_index, dataset, queries):
        h = hnsw.from_cagra(cagra_index)
        d, i = hnsw.search(h, queries[:3], 5)
        for r in range(3):
            want = ((dataset[i[r]] - queries[r]) ** 2).sum(1)
            np.testing.assert_allclose(d[r], want, rtol=1e-4)


class TestBallCover:
    def test_exact_knn(self, dataset, queries, oracle):
        index = ball_cover.build(dataset)
        d, i = ball_cover.knn(index, queries, 10)
        _, want = oracle
        assert calc_recall(np.asarray(i), want) == 1.0

    def test_probe_mode_recall_rises(self, dataset, queries, oracle):
        index = ball_cover.build(dataset, n_landmarks=64)
        _, want = oracle
        _, i_lo = ball_cover.knn(index, queries, 10, n_probes=2)
        _, i_hi = ball_cover.knn(index, queries, 10, n_probes=32)
        r_lo = calc_recall(np.asarray(i_lo), want)
        r_hi = calc_recall(np.asarray(i_hi), want)
        assert r_hi >= max(r_lo, 0.9)

    def test_eps_nn_matches_dense(self, dataset, queries):
        index = ball_cover.build(dataset, n_landmarks=32)
        eps = 5.5
        adj, vd = ball_cover.eps_nn(index, queries, eps)
        want_adj, want_vd = ball_cover.epsilon_neighborhood(
            queries, dataset, eps)
        np.testing.assert_array_equal(np.asarray(adj), np.asarray(want_adj))
        np.testing.assert_array_equal(np.asarray(vd), np.asarray(want_vd))
        assert int(np.asarray(vd).sum()) > 0  # eps chosen to be non-trivial

    def test_radii_cover_members(self, dataset):
        index = ball_cover.build(dataset, n_landmarks=16)
        labels = np.repeat(np.arange(index.ivf.n_lists),
                           np.diff(index.ivf.list_offsets))
        d = np.sqrt(((np.asarray(index.ivf.data) -
                      np.asarray(index.ivf.centers)[labels]) ** 2).sum(1))
        valid = np.asarray(index.ivf.source_ids) >= 0
        labels, d = labels[valid], d[valid]
        assert (d <= np.asarray(index.radii)[labels] + 1e-4).all()
