"""Crash-safe mutable index tier (neighbors/mutable.py + core/wal.py).

Covers the ISSUE 11 acceptance contract:

* WAL framing: roundtrip, torn-tail truncation at the first bad frame,
  CorruptIndexError on mid-log corruption (never silent drops of acked
  data);
* tombstone-filter parity on every family (brute/ivf_flat/ivf_pq/cagra,
  edge AND gather engines), including the k-near-boundary case where
  the tombstoned row was rank 1;
* crash drills: for every named ``CRASH_POINTS`` site, kill at the
  site → ``recover()`` → servable index, every acked upsert/delete
  visible, no torn state loaded — plus a source sweep that FAILS the
  suite when a new ``faults.crash(...)`` site is not in
  ``CRASH_POINTS`` (and therefore not drilled);
* merge lifecycle: upsert+merge == build on the concatenated corpus
  (bit-exact ids on the exact path), mutations racing a merge, and the
  fail-safe arc — a fault-injected merge failure leaves the live index
  serving with a ``merge_abandoned`` event and an open ``mutable.merge``
  breaker that later probes closed.
"""
import os
import pathlib
import re
import struct

import numpy as np
import pytest

import jax.numpy as jnp

from raft_tpu.core import events, faults, wal
from raft_tpu.core.errors import CorruptIndexError, RaftError
from raft_tpu.neighbors import brute_force, cagra, ivf_flat, ivf_pq, mutable
from raft_tpu.ops import guarded
from raft_tpu.serve import debugz, metrics, quality

pytestmark = pytest.mark.faults


def _ambient_kernel_faults() -> bool:
    return any(f.kind in ("kernel_compile", "kernel_fault")
               for f in faults.active())


def _merge(m: mutable.MutableIndex, **kw) -> str:
    """Merge through the guarded path, skipping under the ambient
    faults lane (kernel_compile@* makes every guarded site serve its
    fallback per call — PR 8/9 precedent)."""
    if _ambient_kernel_faults():
        pytest.skip("ambient kernel faults serve guarded sites from the "
                    "fallback")
    return m.merge(**kw)


def _live_ids(m: mutable.MutableIndex) -> set:
    """External ids a search could ever return (sealed alive + delta
    alive) — the test's oracle for acked-write visibility."""
    sealed = set(np.asarray(m._sealed_ids)[m._alive].tolist())
    d = np.asarray(m._d_ids[:m._d_n])[m._d_alive[:m._d_n]]
    return sealed | set(d.tolist())


def _corpus(rng, n, d):
    return rng.normal(size=(n, d)).astype(np.float32)


# ---------------------------------------------------------------------------
class TestWal:
    def _mk(self, tmp_path):
        return wal.WriteAheadLog.create(str(tmp_path / "w.log"))

    def test_roundtrip(self, tmp_path, rng):
        w = self._mk(tmp_path)
        v = _corpus(rng, 3, 4)
        w.append("upsert", np.array([5, 6, 7]), v)
        w.append("delete", np.array([6]))
        w.close()
        records, truncated = wal.replay(str(tmp_path / "w.log"))
        assert truncated == 0
        assert [r[0] for r in records] == ["upsert", "delete"]
        np.testing.assert_array_equal(records[0][1], [5, 6, 7])
        np.testing.assert_allclose(records[0][2], v)
        assert records[1][2] is None

    def test_torn_tail_truncates_and_reopens(self, tmp_path, rng):
        p = str(tmp_path / "w.log")
        w = self._mk(tmp_path)
        w.append("delete", np.array([1]))
        w.close()
        good = os.path.getsize(p)
        with open(p, "ab") as f:      # a frame cut mid-payload
            f.write(struct.pack("<I", 1000) + b"partial")
        records, truncated = wal.replay(p, repair=True)
        assert len(records) == 1 and truncated > 0
        assert os.path.getsize(p) == good
        # the repaired log extends cleanly
        w = wal.WriteAheadLog.open(p)
        w.append("delete", np.array([2]))
        w.close()
        records, truncated = wal.replay(p)
        assert [r[0] for r in records] == ["delete", "delete"]
        assert truncated == 0

    def test_torn_crc_on_last_frame_truncates(self, tmp_path):
        p = str(tmp_path / "w.log")
        w = self._mk(tmp_path)
        w.append("delete", np.array([1]))
        w.append("delete", np.array([2]))
        w.close()
        with open(p, "r+b") as f:     # corrupt the LAST byte (frame 2 CRC)
            f.seek(-1, os.SEEK_END)
            b = f.read(1)
            f.seek(-1, os.SEEK_END)
            f.write(bytes([b[0] ^ 1]))
        records, truncated = wal.replay(p, repair=False)
        assert len(records) == 1 and truncated > 0

    def test_midlog_corruption_raises(self, tmp_path):
        p = str(tmp_path / "w.log")
        w = self._mk(tmp_path)
        w.append("delete", np.array([1]))
        w.append("delete", np.array([2]))
        w.close()
        # flip a byte inside FRAME 1's payload: a later complete frame
        # exists, so this is damaged ACKED data, not a torn tail
        off = len(b"RAFTWAL1") + 4 + 4 + 2
        with open(p, "r+b") as f:
            f.seek(off)
            b = f.read(1)
            f.seek(off)
            f.write(bytes([b[0] ^ 1]))
        with pytest.raises(CorruptIndexError):
            wal.replay(p)
        # closed (non-last) logs may not even have a torn tail
        with open(p, "r+b") as f:
            f.truncate(os.path.getsize(p) - 2)
        with pytest.raises(CorruptIndexError):
            wal.replay(p, allow_torn_tail=False)

    def test_bad_magic(self, tmp_path):
        p = tmp_path / "not.log"
        p.write_bytes(b"GARBAGE!")
        with pytest.raises(CorruptIndexError):
            wal.replay(str(p))

    def test_append_after_failed_write_truncates_garbage(self, tmp_path):
        """A failed append (ENOSPC mid-write) leaves torn un-acked
        bytes; the NEXT append must truncate back to the last good
        frame — an acked retry landing after garbage would be lost (or
        read as mid-log corruption) at recovery."""
        p = str(tmp_path / "w.log")
        w = self._mk(tmp_path)
        w.append("delete", np.array([1]))
        # simulate the torn leftovers of a write that raised mid-frame
        w._f.write(struct.pack("<I", 999) + b"torn")
        w._f.flush()
        w.append("delete", np.array([2]))       # the acked retry
        w.close()
        records, truncated = wal.replay(p)
        assert [int(r[1][0]) for r in records] == [1, 2]
        assert truncated == 0


# ---------------------------------------------------------------------------
class TestMutableBasics:
    def test_upsert_delete_search_vs_reference(self, tmp_path, rng):
        X = _corpus(rng, 200, 12)
        m = mutable.create(tmp_path / "i", X)
        up = _corpus(rng, 30, 12)
        ids = m.upsert(None, up)
        np.testing.assert_array_equal(ids, np.arange(200, 230))
        assert m.delete([3, 8, 205, 9999]) == 3
        # logical live corpus, external-id order
        live_v = np.concatenate([np.delete(X, [3, 8], axis=0),
                                 np.delete(up, [5], axis=0)])
        live_i = np.concatenate([np.delete(np.arange(200), [3, 8]),
                                 np.delete(np.arange(200, 230), [5])])
        ref = brute_force.build(live_v)
        q = _corpus(rng, 16, 12)
        rd, ri = brute_force.search(ref, jnp.asarray(q), 10)
        rd, ri = np.asarray(rd), live_i[np.asarray(ri)]
        md, mi = m.search(q, 10)
        np.testing.assert_array_equal(np.asarray(mi), ri)
        np.testing.assert_allclose(np.asarray(md), rd, rtol=1e-5,
                                   atol=1e-5)

    def test_delete_then_reinsert_is_exact(self, tmp_path, rng):
        X = _corpus(rng, 120, 8)
        m = mutable.create(tmp_path / "i", X)
        q = X[17:18]
        _, i0 = m.search(q, 2)
        assert int(np.asarray(i0)[0, 0]) == 17       # rank 1 = itself
        m.delete([17])
        _, i1 = m.search(q, 2)
        assert 17 not in np.asarray(i1)
        # reinsert id 17 with a DIFFERENT vector: the tombstone must
        # keep masking the sealed copy and serve only the delta copy
        newv = _corpus(rng, 1, 8)
        m.upsert(np.array([17]), newv)
        d2, i2 = m.search(newv, 1)
        assert int(np.asarray(i2)[0, 0]) == 17
        assert float(np.asarray(d2)[0, 0]) < 1e-6
        d3, _ = m.search(q, 120)
        # the ORIGINAL row-17 vector is gone: no ~0 distance for q
        assert float(np.asarray(d3)[0, 0]) > 1e-3

    def test_upsert_overwrite_in_delta(self, tmp_path, rng):
        m = mutable.create(tmp_path / "i", dataset=None, dim=8)
        v1, v2 = _corpus(rng, 1, 8), _corpus(rng, 1, 8)
        m.upsert(np.array([42]), v1)
        m.upsert(np.array([42]), v2)
        assert m.delta_rows == 1                      # old copy is dead
        d, i = m.search(v2, 1)
        assert int(np.asarray(i)[0, 0]) == 42
        assert float(np.asarray(d)[0, 0]) < 1e-6

    def test_empty_errors_and_auto_ids_resume(self, tmp_path, rng):
        m = mutable.create(tmp_path / "i", dataset=None, dim=8)
        with pytest.raises(RaftError):
            m.search(_corpus(rng, 1, 8), 1)
        m.upsert(np.array([100]), _corpus(rng, 1, 8))
        auto = m.upsert(None, _corpus(rng, 2, 8))
        np.testing.assert_array_equal(auto, [101, 102])
        r = mutable.recover(tmp_path / "i")
        auto2 = r.upsert(None, _corpus(rng, 1, 8))    # resumes past 102
        assert int(auto2[0]) == 103

    def test_make_searcher_and_wal_bytes(self, tmp_path, rng):
        X = _corpus(rng, 100, 8)
        m = mutable.create(tmp_path / "i", X)
        fn = mutable.make_searcher(m)
        d, i = fn(X[:4], 3)
        assert np.asarray(i).shape == (4, 3)
        b0 = m.wal_bytes()
        m.upsert(None, _corpus(rng, 2, 8))
        assert m.wal_bytes() > b0

    def test_user_filter_rejected(self, tmp_path, rng):
        from raft_tpu.core.bitset import Bitset

        X = _corpus(rng, 50, 8)
        m = mutable.create(tmp_path / "i", X)
        with pytest.raises(RaftError, match="filter"):
            m.search(X[:2], 3, filter=Bitset.create(50))


# ---------------------------------------------------------------------------
# tier-1 keeps the exact family (the merge-parts fan-out reference) and
# the cagra gather engine; the ≥2s builds (ivf kmeans fits, the
# interpret-mode edge kernel) ride the slow lane per the tier-1 wall
# policy — the tombstone MECHANISM under test is identical (the family
# filter path), and the ivf filter path has its own tier-1 kernel
# parity tests in test_ops.py
_slow = pytest.mark.slow
_FAMILY_CASES = [
    pytest.param(("brute_force", {}, None), id="brute_force"),
    pytest.param(
        ("ivf_flat", {"n_lists": 4, "kmeans_n_iters": 2},
         ivf_flat.SearchParams(n_probes=4)),
        id="ivf_flat", marks=_slow),
    pytest.param(
        ("ivf_pq", {"n_lists": 4, "pq_dim": 4, "pq_bits": 4,
                    "kmeans_n_iters": 2},
         ivf_pq.SearchParams(n_probes=4)),
        id="ivf_pq", marks=_slow),
    pytest.param(
        ("cagra-gather", {"graph_degree": 8,
                          "intermediate_graph_degree": 16},
         cagra.SearchParams(itopk_size=32, engine="gather")),
        id="cagra-gather"),
    pytest.param(
        ("cagra-edge", {"graph_degree": 8, "intermediate_graph_degree": 16},
         cagra.SearchParams(itopk_size=32, engine="edge")),
        id="cagra-edge", marks=_slow),
]


class TestTombstoneParity:
    """A deleted id NEVER appears in results, for every sealed family —
    including at the k=1 boundary where the tombstoned row was rank 1."""

    @pytest.mark.parametrize("case", _FAMILY_CASES)
    def test_deleted_id_never_returned(self, tmp_path, rng, case):
        name, fp, sp = case
        family = name.split("-")[0]
        X = _corpus(rng, 256, 16)
        m = mutable.create(tmp_path / "i", X, family=family,
                           family_params=fp)
        if name == "cagra-edge":
            # the Pallas frontier-expansion engine (interpret mode on
            # CPU) with the in-kernel tombstone penalty
            cagra.prepare_traversal(m.sealed_index, "int8")
        victim = 23
        q = X[victim:victim + 1]
        d0, i0 = m.search(q, 5, params=sp)
        assert int(np.asarray(i0)[0, 0]) == victim   # rank 1 = itself
        runner_up = int(np.asarray(i0)[0, 1])
        m.delete([victim])
        # k=1: the boundary case — the tombstoned row WAS the answer
        _, i1 = m.search(q, 1, params=sp)
        assert int(np.asarray(i1)[0, 0]) != victim
        d5, i5 = m.search(q, 5, params=sp)
        assert victim not in np.asarray(i5)
        if family in ("brute_force", "ivf_flat"):
            # exact / probe-stable families: the old rank 2 is the new
            # rank 1 (ivf_pq is quantized, cagra approximate)
            assert int(np.asarray(i5)[0, 0]) == runner_up
        # tombstones also hold with a delta tier in the fan-out
        m.upsert(None, _corpus(rng, 8, 16))
        _, i6 = m.search(q, 5, params=sp)
        assert victim not in np.asarray(i6)


# ---------------------------------------------------------------------------
class TestCrashDrills:
    def test_crash_site_sweep_matches_drilled_set(self):
        """CI drift guard: every ``faults.crash(...)`` site in
        mutable.py/wal.py must be a drilled ``CRASH_POINTS`` entry — a
        new crash point without a kill-and-recover drill fails here."""
        import raft_tpu

        root = pathlib.Path(raft_tpu.__file__).parent
        found = set()
        for rel in ("neighbors/mutable.py", "core/wal.py"):
            src = (root / rel).read_text()
            found |= set(re.findall(
                r'faults\.crash\(\s*\n?\s*"([^"]+)"', src))
            if re.search(r"faults\.crash\(APPEND_SITE\)", src):
                found.add(wal.APPEND_SITE)
        assert found == set(mutable.CRASH_POINTS), (
            f"crash sites drifted: source has {sorted(found)}, "
            f"CRASH_POINTS drills {sorted(mutable.CRASH_POINTS)} — add "
            "new sites to mutable.CRASH_POINTS so the kill-and-recover "
            "drill below covers them")

    @pytest.mark.parametrize("site", mutable.CRASH_POINTS)
    def test_kill_at_site_then_recover(self, tmp_path, rng, site):
        """Kill at the named site → recover() → servable, every acked
        write visible, no torn state loaded."""
        if site.startswith("mutable.merge") and _ambient_kernel_faults():
            pytest.skip("ambient kernel faults pre-empt the guarded "
                        "merge path")
        X = _corpus(rng, 120, 8)
        p = tmp_path / "i"
        m = mutable.create(p, X)
        acked_v = _corpus(rng, 3, 8)
        m.upsert(np.array([500, 501, 502]), acked_v)     # acked
        m.delete([5, 501])                                # acked
        died = False
        try:
            with faults.inject("crash_point", site, count=1):
                if site.startswith("mutable.merge"):
                    m.merge()
                else:
                    m.upsert(np.array([900]), _corpus(rng, 1, 8))
        except faults.InjectedCrash:
            died = True
        assert died, f"crash point {site} never fired"
        r = mutable.recover(p)
        live = _live_ids(r)
        assert {500, 502} <= live and 501 not in live and 5 not in live
        # acked upserts SERVE (not just bookkeeping): the new vector is
        # found at ~0 distance, the deleted id never surfaces
        d, i = r.search(acked_v[0:1], 1)
        assert int(np.asarray(i)[0, 0]) == 500
        assert float(np.asarray(d)[0, 0]) < 1e-6
        _, i5 = r.search(X[5:6], 5)
        assert 5 not in np.asarray(i5)
        ev = [e for e in events.recent(kind="wal_recovered")
              if e["site"] == r.name]
        assert ev, "recover() must flight-record wal_recovered"

    def test_wal_torn_tail_drill(self, tmp_path, rng):
        """A write cut mid-frame: recovery truncates the torn tail, the
        acked prefix survives, and the log extends cleanly after."""
        X = _corpus(rng, 100, 8)
        p = tmp_path / "i"
        m = mutable.create(p, X)
        m.upsert(np.array([700]), _corpus(rng, 1, 8))    # acked
        with pytest.raises(faults.InjectedCrash):
            with faults.inject("wal_torn_tail", wal.APPEND_SITE, count=1):
                m.upsert(np.array([701]), _corpus(rng, 1, 8))  # never acked
        r = mutable.recover(p)
        live = _live_ids(r)
        assert 700 in live and 701 not in live
        ev = [e for e in events.recent(kind="wal_recovered")
              if e["site"] == r.name]
        assert ev and ev[-1]["truncated_bytes"] > 0
        r.upsert(np.array([702]), _corpus(rng, 1, 8))
        assert 702 in _live_ids(mutable.recover(p))

    def test_corrupt_segment_rebuilt_from_snapshot(self, tmp_path, rng):
        """A CRC-corrupt segment file is derived state: recover()
        rebuilds it from the snapshot corpus instead of refusing."""
        X = _corpus(rng, 100, 8)
        p = tmp_path / "i"
        m = mutable.create(p, X)
        seg = p / m._seg_name(m.generation)
        raw = bytearray(seg.read_bytes())
        raw[len(raw) // 2] ^= 0x01
        seg.write_bytes(bytes(raw))
        r = mutable.recover(p)
        assert r.sealed_rows == 100
        _, i = r.search(X[:3], 1)
        np.testing.assert_array_equal(np.asarray(i)[:, 0], [0, 1, 2])


# ---------------------------------------------------------------------------
class TestMergeLifecycle:
    def test_upsert_merge_equals_build_bit_exact(self, tmp_path, rng):
        """The ivf extend-deprecation satellite: MutableIndex.upsert +
        merge == build on the concatenated corpus — bit-exact ids at
        fixed k on the exact path."""
        X = _corpus(rng, 300, 16)
        up = _corpus(rng, 40, 16)
        m = mutable.create(tmp_path / "i", X)
        m.upsert(None, up)
        q = _corpus(rng, 12, 16)
        _, i_pre = m.search(q, 10)
        assert _merge(m) == "committed"
        d_post, i_post = m.search(q, 10)
        # pre-merge fan-out and post-merge single-segment agree exactly
        np.testing.assert_array_equal(np.asarray(i_pre),
                                      np.asarray(i_post))
        ref = brute_force.build(np.concatenate([X, up]))
        rd, ri = brute_force.search(ref, jnp.asarray(q), 10)
        np.testing.assert_array_equal(np.asarray(i_post), np.asarray(ri))
        np.testing.assert_allclose(np.asarray(d_post), np.asarray(rd),
                                   rtol=1e-5, atol=1e-5)

    def test_merge_folds_retires_and_records(self, tmp_path, rng):
        X = _corpus(rng, 150, 8)
        p = tmp_path / "i"
        m = mutable.create(p, X)
        m.upsert(None, _corpus(rng, 10, 8))
        m.delete([0, 1])
        wal_before = m.wal_bytes()
        assert wal_before > 0
        gen0 = m.generation
        assert _merge(m) == "committed"
        assert m.generation == gen0 + 1
        assert m.delta_rows == 0 and m.tombstones == 0
        assert m.sealed_rows == 158
        assert m.wal_bytes() < wal_before          # rotated fresh
        # old generation retired from disk
        names = set(os.listdir(p))
        assert m._seg_name(gen0) not in names
        assert m._snap_name(gen0) not in names
        kinds = {e["kind"] for e in events.recent()
                 if e.get("site") == m.name}
        assert {"merge_started", "merge_committed"} <= kinds
        # and the merged state survives a restart
        r = mutable.recover(p)
        assert (r.generation, r.sealed_rows, r.delta_rows) == (
            m.generation, 158, 0)

    def test_mutations_racing_the_merge(self, tmp_path, rng):
        """Writes landing between the merge snapshot and the flip are
        neither lost nor double-served: the rotated WAL carries them,
        the flipped segment re-tombstones the ids they touched."""
        X = _corpus(rng, 150, 8)
        p = tmp_path / "i"
        m = mutable.create(p, X)
        m.upsert(None, _corpus(rng, 10, 8))
        mid_new = _corpus(rng, 1, 8)

        def mid_merge():
            m.upsert(np.array([7]), mid_new)       # override a sealed row
            m.delete([11])                          # delete a sealed row
            m.upsert(np.array([800]), mid_new)      # brand-new id

        m._after_snapshot_hook = mid_merge
        try:
            assert _merge(m) == "committed"
        finally:
            m._after_snapshot_hook = None
        live = _live_ids(m)
        assert 11 not in live and {7, 800} <= live
        d, i = m.search(mid_new, 2)
        assert {int(x) for x in np.asarray(i)[0]} == {7, 800}
        assert float(np.asarray(d)[0, 0]) < 1e-6   # the NEW vector serves
        _, i11 = m.search(X[11:12], 5)
        assert 11 not in np.asarray(i11)
        # recovery replays the same story
        r = mutable.recover(p)
        assert 11 not in _live_ids(r) and {7, 800} <= _live_ids(r)
        d2, i2 = r.search(mid_new, 2)
        assert {int(x) for x in np.asarray(i2)[0]} == {7, 800}

    def test_merge_failure_is_failsafe(self, tmp_path, rng, monkeypatch):
        """The acceptance drill: a fault-injected merge failure leaves
        the live index serving, records merge_abandoned, opens the
        mutable.merge breaker (backing off further ticks), and a later
        probe commits and re-closes it."""
        if _ambient_kernel_faults():
            pytest.skip("ambient kernel faults pre-empt the guarded "
                        "merge path")
        now = {"t": 0.0}
        monkeypatch.setattr(guarded, "_clock", lambda: now["t"])
        X = _corpus(rng, 120, 8)
        m = mutable.create(tmp_path / "i", X)
        m.upsert(None, _corpus(rng, 6, 8))
        n0 = metrics.counter("mutable.merges.abandoned").value
        try:
            with faults.inject("io_error", "core.serialize.*"):
                assert m.merge() == "backoff"       # failed -> abandoned
            assert m._last_merge["verdict"] == "abandoned"
            assert metrics.counter(
                "mutable.merges.abandoned").value == n0 + 1
            assert [e for e in events.recent(kind="merge_abandoned")
                    if e["site"] == m.name]
            b = guarded.breaker_snapshot()[mutable.MERGE_SITE]
            assert b["state"] == "open"
            # live index untouched and still serving both tiers
            assert m.delta_rows == 6 and m.generation == 1
            _, i = m.search(X[:2], 3)
            assert np.asarray(i).shape == (2, 3)
            # breaker open: the maintenance tick backs off, no new event
            assert m.merge() == "backoff"
            # fault cleared + probation elapsed -> the probe merge runs,
            # commits, and re-closes the breaker
            now["t"] += b["next_probe_in_s"] + 1.0
            assert m.merge() == "committed"
            assert guarded.breaker_snapshot()[
                mutable.MERGE_SITE]["state"] == "closed"
            assert m.delta_rows == 0 and m.generation == 2
        finally:
            guarded.reset()

    def test_deadline_abandons(self, tmp_path, rng):
        if _ambient_kernel_faults():
            pytest.skip("ambient kernel faults pre-empt the guarded "
                        "merge path")
        X = _corpus(rng, 120, 8)
        m = mutable.create(tmp_path / "i", X)
        m.upsert(None, _corpus(rng, 4, 8))
        try:
            assert m.merge(deadline_s=1e-9) == "backoff"
            assert m._last_merge["verdict"] == "abandoned"
            assert "deadline" in m._last_merge["reason"]
            assert m.generation == 1 and m.delta_rows == 4
        finally:
            guarded.reset()

    def test_recall_floor_abandons(self, tmp_path, rng, monkeypatch):
        if _ambient_kernel_faults():
            pytest.skip("ambient kernel faults pre-empt the guarded "
                        "merge path")
        X = _corpus(rng, 120, 8)
        m = mutable.create(tmp_path / "i", X)
        m.upsert(None, _corpus(rng, 4, 8))
        m.merge_recall_floor = 1.1      # unattainable: force the check
        try:
            assert m.merge() == "backoff"
            assert m._last_merge["verdict"] == "abandoned"
            assert "recall" in m._last_merge["reason"]
        finally:
            guarded.reset()

    def test_duplicate_vectors_still_merge(self, tmp_path, rng):
        """Exact-duplicate rows under distinct ids tie arbitrarily in
        id — the post-merge check scores distances, so a dedup-free
        corpus must not abandon every merge forever."""
        if _ambient_kernel_faults():
            pytest.skip("ambient kernel faults pre-empt the guarded "
                        "merge path")
        base = _corpus(rng, 60, 8)
        X = np.concatenate([base, base])        # 50% exact duplicates
        m = mutable.create(tmp_path / "i", X)
        m.upsert(None, base[:8])                # triplicate some rows
        try:
            assert m.merge() == "committed"
        finally:
            guarded.reset()
        assert m._last_merge["merge_recall"] == 1.0

    def test_prewarm_compiles_the_served_request(self, tmp_path, rng):
        """The flip's pre-warm must trace the executable traffic is
        ACTUALLY using (last shape + params + engine opts), not the
        defaults — else the first post-flip request pays the compile
        the pre-warm exists to prevent."""
        X = _corpus(rng, 100, 8)
        m = mutable.create(tmp_path / "i", X)
        m.upsert(None, _corpus(rng, 4, 8))
        calls = []
        orig = m._search_sealed

        def spy(idx, q, k, params, filt, opts):
            calls.append((tuple(q.shape), k, params, dict(opts)))
            return orig(idx, q, k, params, filt, opts)

        m._search_sealed = spy
        m.search(X[:6], 3, precision="default")
        calls.clear()
        try:
            assert _merge(m) == "committed"
        finally:
            guarded.reset()
        warm = [(shape, k, o) for shape, k, _p, o in calls
                if o.get("precision") == "default"]
        assert warm and warm[-1][0] == (6, 8) and warm[-1][1] == 3

    def test_concurrent_merge_call_keeps_the_flag(self, tmp_path, rng):
        """A second merge() landing mid-merge returns "in_progress" and
        must NOT clear the in-flight merge's flag on its way out —
        mutations raced after such a clear would skip _during and
        survive the flip as live stale sealed copies."""
        X = _corpus(rng, 60, 8)
        m = mutable.create(tmp_path / "i", X)
        m._merging = True                  # an in-flight merge
        assert m._merge_once(None) == "in_progress"
        assert m._merging is True
        m._merging = False

    def test_torn_unacked_tail_survives_rotation(self, tmp_path, rng):
        """A failed append's torn leftovers in the active log must be
        sealed away when a merge rotates it out — a closed log is
        replayed with allow_torn_tail=False, and un-acked garbage must
        not make the whole index unrecoverable."""
        X = _corpus(rng, 80, 8)
        p = tmp_path / "i"
        m = mutable.create(p, X)
        m.upsert(np.array([300]), _corpus(rng, 1, 8))      # acked
        # a write that died mid-frame (exception propagated, un-acked)
        m._wal._f.write(struct.pack("<I", 999) + b"torn")
        m._wal._f.flush()
        died = False
        try:   # the rotation seals the old log, then the crash fires
            with faults.inject("crash_point", "mutable.merge.build",
                               count=1):
                m.merge()
        except faults.InjectedCrash:
            died = True
        if died:   # guarded path may be pre-empted in the faults lane
            r = mutable.recover(p)      # must NOT raise CorruptIndexError
            assert 300 in _live_ids(r)

    def test_maintenance_thresholds(self, tmp_path, rng):
        if _ambient_kernel_faults():
            pytest.skip("ambient kernel faults pre-empt the guarded "
                        "merge path")
        X = _corpus(rng, 100, 8)
        m = mutable.create(tmp_path / "i", X)
        m.merge_rows = 5
        assert m.maintenance() is None              # below threshold
        m.upsert(None, _corpus(rng, 6, 8))
        assert m.should_merge()
        try:
            assert m.maintenance() == "committed"   # SnapshotWriter hook
        finally:
            guarded.reset()
        assert not m.should_merge()


# ---------------------------------------------------------------------------
class TestOpsSurface:
    def test_debugz_health_and_events(self, tmp_path, rng):
        X = _corpus(rng, 90, 8)
        # unique basename: ops_snapshot keys on it, and not-yet-GC'd
        # indexes from other tests (all named "i") would collide
        m = mutable.create(tmp_path / "ops-drill-idx", X)
        m.upsert(None, _corpus(rng, 3, 8))
        m.delete([2])
        snap = mutable.ops_snapshot()["indexes"]
        ent = snap[m.name]
        assert (ent["delta_rows"], ent["tombstones"]) == (3, 1)
        assert ent["wal_bytes"] > 0 and ent["generation"] == 1
        # rides the debugz surface, strict-JSON end to end
        import json

        s = debugz.snapshot(registry=metrics.Registry())
        assert m.name in s["mutable"]
        json.dumps(s, allow_nan=False)
        txt = debugz.render_text(registry=metrics.Registry())
        assert "mutable indexes" in txt and m.name in txt
        # quality.health dispatches the mutable tier
        rep = quality.health(m)
        assert rep["family"] == "mutable"
        assert rep["sealed"]["family"] == "brute_force"
        # mutation events are in the flight-recorder tail
        kinds = {e["kind"] for e in events.recent()
                 if e.get("site") == m.name}
        assert {"upsert", "delete"} <= kinds

    def test_extend_docstrings_point_to_mutable(self):
        """The deprecation-pointer satellite stays put."""
        assert "MutableIndex" in ivf_flat.extend.__doc__
        assert "MutableIndex" in ivf_pq.extend.__doc__


# ---------------------------------------------------------------------------
class TestHotPathSync:
    def test_search_dispatch_does_not_synchronize(self, tmp_path, rng,
                                                  monkeypatch):
        """ISSUE 12 hot-path sync audit: a mutable-tier search dispatch
        (sealed + delta fan-out + merge) must not call
        ``block_until_ready`` — results stay asynchronous until the
        caller materializes them; the only serve-path syncs are the
        SAMPLED probes (batcher device stage, merge pre-warm)."""
        import jax

        X = _corpus(rng, 96, 8)
        m = mutable.create(tmp_path / "nosync-idx", X)
        m.upsert(None, _corpus(rng, 5, 8))      # populate the delta tier
        q = X[:4]
        m.search(q, 4)                          # warm executables first
        syncs = []
        orig = jax.block_until_ready

        def spy(x):
            syncs.append(x)
            return orig(x)

        monkeypatch.setattr(jax, "block_until_ready", spy)
        d, i = m.search(q, 4)
        assert not syncs, "mutable search synchronized on the hot path"
        assert np.asarray(i).shape == (4, 4)    # results still land
