"""Fused brute-force engine exactness vs the GEMM reference engine.

The acceptance bar for the streaming fused kernel (ops/fused_knn.py) is
bit-identical results against the matmul engine — index ORDER included,
ties broken smallest-column exactly as ``lax.top_k`` breaks them — across
every expanded metric, storage dtype, filter/validity mask and edge
shape. All of it runs on CPU: the kernel in interpret mode (the same
code Mosaic compiles on TPU), the >128k dispatch plumbing through the
guarded XLA fallback (ops/guarded.py), so tier-1 exercises the ungated
race path without TPU hardware in the loop.

Budget note: tests deliberately share one (m, n, d, k) geometry wherever
the assertion allows it — interpret-mode kernel compiles dominate the
wall, and a shared shape means a shared cached executable.
"""
import numpy as np
import pytest

import jax.numpy as jnp

from raft_tpu.core import faults
from raft_tpu.core.bitset import Bitset
from raft_tpu.neighbors import brute_force

METRICS = ["sqeuclidean", "euclidean", "cosine", "inner_product"]
K = 20   # shared-geometry k; >16 so the kernel extract is a fori_loop
         # (one loop body per merge site instead of k unrolled passes:
         # interpret-mode compile wall is what tier-1 pays for)


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(17)
    return (rng.standard_normal((1900, 24)).astype(np.float32),
            rng.standard_normal((40, 24)).astype(np.float32))


def assert_engines_match(index, q, k, rtol=1e-5, **opts):
    """pallas (fused) vs matmul (GEMM+top_k reference): identical index
    arrays (order included) and matching distances."""
    vp, ip = brute_force.search(index, q, k, algo="pallas", **opts)
    vm, im = brute_force.search(index, q, k, algo="matmul", **opts)
    np.testing.assert_array_equal(np.asarray(ip), np.asarray(im))
    np.testing.assert_allclose(np.asarray(vp), np.asarray(vm),
                               rtol=rtol, atol=1e-5)
    return np.asarray(ip)


class TestFusedEngineExactness:
    @pytest.mark.parametrize("metric", METRICS)
    def test_metric_parity(self, data, metric):
        x, q = data
        index = brute_force.build(x, metric=metric)
        assert_engines_match(index, q, K)

    def test_tie_order_matches_topk(self, data):
        # quantized coordinates force massive distance ties; the fused
        # extraction must retire them smallest-column-first, exactly
        # lax.top_k's order (not merely the same index SET). Same
        # geometry as test_metric_parity: executables are cache hits.
        rng = np.random.default_rng(5)
        x = rng.integers(-3, 4, data[0].shape).astype(np.float32)
        q = rng.integers(-3, 4, data[1].shape).astype(np.float32)
        for metric in ("sqeuclidean", "inner_product"):
            index = brute_force.build(x, metric=metric)
            assert_engines_match(index, q, K)

    @pytest.mark.parametrize("dtype", ["bfloat16", "int8"])
    def test_storage_dtype_parity(self, data, dtype):
        # low-precision corpora stream through the kernel in their
        # stored width; the math must match the GEMM engine's
        # fused-convert path (uint8 covered in test_brute_force)
        x, q = data
        index = brute_force.build(x, dtype=dtype)
        assert_engines_match(index, q, K, rtol=1e-4)

    def test_filter_and_valid_rows_parity(self, data):
        x, q = data
        index = brute_force.build(x)
        rng = np.random.default_rng(3)
        keep = rng.random(len(x)) > 0.5
        got = assert_engines_match(index, q, K,
                                   filter=Bitset.from_mask(jnp.asarray(keep)))
        assert keep[got[got >= 0]].all()
        got = assert_engines_match(index, q, K,
                                   valid_rows=jnp.asarray(700))
        assert (got < 700).all()

    def test_k_edges(self, data):
        x, q = data
        index = brute_force.build(x)
        assert_engines_match(index, q, 1)     # k=1: single-slot buffer
        assert_engines_match(index, q, 128)   # k=128: full-lane buffer

    def test_shapes_off_tile_multiples(self):
        # n and m straddling the tile boundaries exercise the pad +
        # penalty row (pad rows must never surface as results)
        rng = np.random.default_rng(8)
        x = rng.standard_normal((1027, 17)).astype(np.float32)
        q = rng.standard_normal((13, 17)).astype(np.float32)
        index = brute_force.build(x)
        got = assert_engines_match(index, q, 20)
        assert (got < 1027).all()

    def test_above_old_gate_interpret(self, monkeypatch):
        """n just above the removed 128k cap, through the REAL kernel
        (interpret mode; one corpus-wide tile keeps the grid one step)."""
        monkeypatch.setenv("RAFT_TPU_FUSED_TILES", "8,163840")
        rng = np.random.default_rng(13)
        x = rng.standard_normal((131_200, 8)).astype(np.float32)
        q = rng.standard_normal((8, 8)).astype(np.float32)
        index = brute_force.build(x)
        assert_engines_match(index, q, 3)

    def test_above_old_gate_guarded_fallback(self):
        """The ungated dispatch path at >128k rows with the kernel
        failing: guarded_call must serve the exact GEMM fallback (the
        plumbing the serving stack relies on), without demoting the site
        for later calls (injected faults simulate per-call failure)."""
        rng = np.random.default_rng(14)
        x = rng.standard_normal((131_200, 8)).astype(np.float32)
        q = rng.standard_normal((8, 8)).astype(np.float32)
        index = brute_force.build(x)
        vm, im = brute_force.search(index, q, 3, algo="matmul")
        with faults.inject("kernel_compile", "brute_force.fused"):
            vp, ip = brute_force.search(index, q, 3, algo="pallas")
        np.testing.assert_array_equal(np.asarray(ip), np.asarray(im))
        np.testing.assert_allclose(np.asarray(vp), np.asarray(vm),
                                   rtol=1e-6)
        from raft_tpu.ops.guarded import demoted_sites

        assert "brute_force.fused" not in demoted_sites()

    def test_query_chunking_matches_single_dispatch(self, data,
                                                    monkeypatch):
        # a chunk smaller than m routes through the lax.map path; results
        # must be independent of the chunking
        x, q = data
        index = brute_force.build(x)
        v1, i1 = brute_force.search(index, q, K, algo="pallas")
        monkeypatch.setenv("RAFT_TPU_FUSED_QUERY_CHUNK", "16")
        v2, i2 = brute_force.search(index, q, K, algo="pallas")
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
        np.testing.assert_array_equal(np.asarray(v1), np.asarray(v2))

    def test_prepare_fused_caches_aligned_corpus(self, data):
        x, q = data
        index = brute_force.build(x)
        brute_force.prepare_fused(index)
        key, d_pad, norms_pad, base_pen, scales_pad = index._fused_pad
        assert d_pad.shape[0] % 128 == 0 and d_pad.shape[1] % 128 == 0
        assert np.isinf(np.asarray(base_pen)[len(x):]).all()
        assert not np.isinf(np.asarray(base_pen)[: len(x)]).any()
        # idempotent for the same tile geometry
        again = brute_force.prepare_fused(index)
        assert index._fused_pad[0] == key and again is None

    @pytest.mark.slow
    def test_500k_fused_interpret(self, monkeypatch):
        """Corpus at the bench part scale through the real kernel
        (interpret; wide tiles bound the unrolled grid)."""
        monkeypatch.setenv("RAFT_TPU_FUSED_TILES", "8,65536")
        rng = np.random.default_rng(15)
        x = rng.standard_normal((500_000, 8)).astype(np.float32)
        q = rng.standard_normal((8, 8)).astype(np.float32)
        index = brute_force.build(x)
        assert_engines_match(index, q, 10)
